"""Sharded-frontier BFS over a ``jax.sharding.Mesh`` (v3).

The TPU-native replacement for TLC's shared-memory worker threads
(``tlc -workers N``, SURVEY.md §5.8): each chip owns the slice of
fingerprint space ``fp mod D`` (D = mesh size). A wave expands the whole
per-chip frontier by sub-stepping a cursor in ``chunk``-sized chunks; each
chunk is one ``shard_map``-ed program per chip:

    slice `chunk` frontier rows -> expand (vmap over per-action kernels)
    -> compact valid successor lanes -> canonical fingerprints -> route
    each candidate to its owner chip (``fp mod D``) via ``jax.lax.
    all_to_all`` over ICI -> local dedup (probe the chip's LSM seen-runs,
    first-occurrence) -> append survivors to the local next-frontier and
    their (parent shard, parent lgid, candidate) rows to the local
    journal -> batched invariant evaluation folding the first-violating
    journal index per invariant -> emit the chip's new fingerprints as
    one sorted run.

The per-chip seen-set is the same LSM of sorted runs as DeviceBFS
(round-4 redesign, see checker/device_bfs.py): runs live as [D, lanes]
sharded arrays so every merge/consolidation is a batched per-chip sort
with no collectives; the binary-counter cascade is identical on every
chip (all chips insert one run per chunk), so one host-side occupancy
drives the whole mesh. This removes the per-chunk FCAP-lane sort and the
per-wave SCAP-lane finalize of v2 — per-chunk dedup cost is independent
of total state count.

Parent pointers cross shards (a successor's owner is unrelated to its
parent's shard), so journal entries address states as (shard, local gid);
the parent shard is implicit in the all-to-all block structure (received
rows [d*RC:(d+1)*RC] came from chip d) and is never routed.

Checkpoint/resume (round-4 verdict Next #3): same .npz scheme as
DeviceBFS with per-shard arrays — but the payload is MESH-PORTABLE
(elastic-mesh PR): every per-shard array is a segment routable by
``fp mod D`` (the journal carries each row's fingerprint in ``jfp``
exactly for this), the recorded ``/D=<n>/`` ident component is
provenance rather than identity, and a load-time reshard pass
(``_reshard_payload``) re-routes every segment when the resuming mesh
size differs — D=8 -> D=4 -> D=1 all resume with bit-identical counts.
Pre-``jfp`` checkpoints reshard too: ``_recover_journal_fps`` rebuilds
the journal fingerprints by topological replay through the model's
transition function. On capacity overflow or shard loss the abort path
spills a WAVE-START checkpoint by subtracting the aborted wave's
fingerprints back out of the LSM export (``_wave_start_seen``), so
supervised recoveries lose zero work — matching DeviceBFS semantics.

State counts are exact and deterministic; within-wave discovery ORDER
differs from the sequential driver (first-occurrence tie-breaking is by
owner chip, then source chip), which can pick a different — equally
shortest — counterexample.
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    _shard_map = jax.shard_map
    _SHARD_MAP_KW: dict = {}
except AttributeError:  # 0.4.x keeps it in experimental; its replication
    # checker has no rule for while_loop (the memo's blocked canon), so
    # disable the static check there — it is a check, not a semantic.
    from jax.experimental.shard_map import shard_map as _shard_map
    _SHARD_MAP_KW = {"check_rep": False}

from ..checker.lsm import CanonMemo, RunLSM, pow2_at_least
from ..obs import MemWatch, NULL_TELEMETRY
from ..obs.events import hashv_of
from ..checker.util import (
    GROWTH, HEADROOM, I32_MAX, dense_prefix_sel, emit_append,
    next_cap as _next_cap, probe_sorted as _probe,
)
from ..ops.hashing import (
    U64_MAX, eq_u64, ne_u64, sort_u64, sort_u64_with_idx, split_u64,
)
from ..ops.symmetry import Canonicalizer
from ..resilience import ckpt as rckpt
from ..resilience.errors import CapacityOverflow, ShardLost, ShardStall

AXIS = "shards"


@dataclass
class ShardedResult:
    distinct: int
    total: int
    depth: int
    depth_counts: list[int]
    violation_invariant: str | None
    seconds: float
    states_per_sec: float
    terminal: int = 0
    exhausted: bool = True
    trace: list[tuple[str, dict]] | None = None
    metrics: list[dict] | None = None  # per-wave (SURVEY.md §5.5)
    # fleet aggregates: canon-memo hits/rate summed over shards plus
    # per-shard skew (always populated; cheap host arithmetic)
    stats: dict | None = None
    # fleet-summed per-action [enabled, fired, new-distinct] in
    # ACTION_NAMES rank order; None for models without the contract
    coverage: list[list[int]] | None = None
    # why the run ended (obs.events.EXIT_CAUSES vocabulary); the CLI
    # maps "preempted" to exit code 4
    exit_cause: str | None = None


class ShardedBFS:
    """Multi-chip exhaustive BFS with per-chip frontier/seen-runs/journal.

    Capacities (all per device):
      chunk          frontier states expanded per chunk step
      valid_per_state  compaction budget (avg valid successors per state)
      route_cap      all-to-all slots per (src, dst) pair per chunk step;
                     defaults to the compaction budget, which makes route
                     overflow impossible (a chunk yields at most VC
                     candidates, all of which could share one owner)
      frontier_cap   per-wave distinct states (grows, multiple of chunk)
      seen_cap       initial per-chip LSM lane budget (bound: max_seen_cap)
      journal_cap    journal rows = owned distinct states beyond Init
    """

    GROWTH = GROWTH
    HEADROOM = HEADROOM
    # overflow-bit vocabulary for the stats word (chunk-step assembly);
    # SEEN_OVF_BIT is synthetic — the host TOPSZ guard raises it, the
    # device never sets it
    OVF_NAMES = (
        (1, "msg"), (2, "valid"), (4, "route"), (8, "frontier"),
        (16, "journal"),
    )
    SEEN_OVF_BIT = 32

    # Donation contract (audited by `raft_tpu lint`, pass `donation`):
    # every capacity-shaped per-wave carry must alias an output of the
    # program that rebinds it. The frontier is read-only within a wave
    # (host-swapped with next_buf at the wave boundary), fc/bl/cursor are
    # scalars-per-shard, and occ plus the LSM runs are reused across
    # chunks — none of those donate.
    #   chunk: next_buf, jps, jpl, jcand, jfp, viol, stats, memo, cov
    CHUNK_DONATE = (2, 3, 4, 5, 6, 7, 8, 9, 10)
    # timeline stages (--timeline sampled waves): memo through pre, the
    # routed payloads through exchange, the state carries through post
    TL_DONATE = {
        "pre": (2,),
        "exchange": (0, 1),
        "post": (2, 3, 4, 5, 6, 7, 8, 9),
    }

    def __init__(
        self,
        model,
        invariants: tuple[str, ...] = (),
        symmetry: bool = True,
        devices=None,
        chunk: int = 256,
        valid_per_state: int = 16,
        valid_per_group: float | dict | None = None,
        route_cap: int | None = None,
        frontier_cap: int = 1 << 12,
        seen_cap: int = 1 << 16,
        journal_cap: int | None = None,
        max_frontier_cap: int = 1 << 20,
        max_seen_cap: int = 1 << 24,
        max_journal_cap: int = 1 << 24,
        canon_memo_cap: int = 1 << 21,
    ):
        # constructor kwargs, captured before any normalization, so the
        # supervisor/fleet can rebuild this engine with overrides
        # (grown caps, a shrunk device list after a shard loss)
        self._ctor_kw = {k: v for k, v in locals().items() if k != "self"}
        self.model = model
        self.invariants = tuple(invariants)
        # rank-indexed coverage rows; 0 for models without the
        # ACTION_NAMES contract (coverage then disabled)
        self.n_actions = len(getattr(model, "ACTION_NAMES", ()))
        devices = devices if devices is not None else jax.devices()
        self.D = len(devices)
        # the u32-decomposed fp%D owner routing is exact only for D<=2^16
        assert self.D <= (1 << 16), "owner routing supports at most 2^16 shards"
        self.mesh = Mesh(np.array(devices), (AXIS,))
        self.chunk = chunk
        self.A = model.A
        self.W = model.layout.W
        self.VC = min(chunk * self.A, chunk * valid_per_state)
        # guard-first sparse expansion (SparseExpandMixin models): see
        # checker/device_bfs.py — same two-phase contract per shard
        self._sparse = hasattr(model, "sparse_apply")
        self.valid_per_group = valid_per_group
        self._plan = (
            model.sparse_plan(chunk, self.VC, valid_per_group)
            if self._sparse
            else None
        )
        # a chunk receives at most D*RC routed lanes; RC defaults to VC
        self.RC = route_cap if route_cap is not None else self.VC
        # emit drop-region rows past FCAP/JCAP: one chunk appends at most
        # the D*RC received lanes (checker/util.py emit_append)
        self.EPAD = self.D * self.RC
        frontier_cap = ((frontier_cap + chunk - 1) // chunk) * chunk
        self.FCAP = frontier_cap
        self.JCAP = journal_cap if journal_cap is not None else seen_cap
        self.MAX_FCAP = max(max_frontier_cap, frontier_cap)
        self.MAX_SCAP = max(max_seen_cap, seen_cap)
        self.MAX_JCAP = max(max_journal_cap, self.JCAP)
        # LSM geometry: a chunk inserts the D*RC received lanes' worth of
        # new fps at most, but only its own VC-compacted candidates can
        # be new — the run size is the receive width. Shared
        # implementation (checker/lsm.py): runs are [D, lanes] sharded
        # arrays, merges are collective-free per-chip sorts.
        self.R0 = pow2_at_least(self.D * self.RC)
        self.SCAP = self.MAX_SCAP
        self.canon = Canonicalizer.for_model(model, symmetry=symmetry)
        self._sharding = NamedSharding(self.mesh, P(AXIS))
        self._lsm = RunLSM(
            r0=self.R0, topsz=pow2_at_least(self.MAX_SCAP),
            lead_shape=(self.D,),
            put=lambda h: jax.device_put(h, self._sharding),
            jit_kw={"out_shardings": self._sharding},
        )
        self.TOPSZ = self._lsm.TOPSZ
        # canon memo is PER SHARD ([D, MCAP, 2]): successors are memoized
        # on the chip that GENERATES them, keyed by the raw view hash,
        # before the all-to-all routes canonical fps to their owners —
        # so no memo state ever crosses ICI. Custom canonicalizers
        # without the memo surface fall back to the unmemoized path.
        self._use_memo = (
            canon_memo_cap > 0
            and hasattr(self.canon, "fingerprints_memo")
        )
        self._memo = CanonMemo(
            canon_memo_cap if self._use_memo else 1,
            lead_shape=(self.D,),
            put=lambda h: jax.device_put(h, self._sharding),
        )
        self.MCAP = self._memo.MCAP

        self._chunk_fn_cache: dict[int, object] = {}
        # wave-timeline observatory: separately dispatched pre / exchange
        # / post programs for sampled waves (--timeline); the carries
        # donate exactly as in the fused chunk program.
        self._tl_pre_ex: tuple | None = None
        self._tl_post_cache: dict[int, object] = {}
        self._occ_cache: dict[bytes, object] = {}
        self._journals = None  # (jps, jpl, jcand) per shard after run()
        self._init_by_shard = None

    # ---------------- LSM adapters (per-chip [D, lanes] runs) ----

    def _occ_dev(self):
        """Occupancy flags as a device array, uploaded once per distinct
        pattern (a fresh upload per chunk is a whole tunnel dispatch —
        same cache as DeviceBFS._occ_dev)."""
        key = bytes(self._lsm.occ)
        arr = self._occ_cache.get(key)
        if arr is None:
            arr = jnp.asarray(np.asarray(self._lsm.occ, dtype=bool))
            self._occ_cache[key] = arr
        return arr

    def _lsm_export(self) -> list[np.ndarray]:
        """Per-chip sorted real fingerprints (checkpoint format)."""
        return self._lsm.export_real()

    def _lsm_seed(self, per_shard: list[np.ndarray]):
        n = max((len(a) for a in per_shard), default=0)
        h = np.full((self.D, max(n, 1)), np.uint64(U64_MAX))
        for d, a in enumerate(per_shard):
            h[d, : len(a)] = np.sort(a.astype(np.uint64))
        self._lsm.seed(h)

    # ---------------- device programs (per chip under shard_map) ----------

    def _get_chunk_fn(self, n_runs: int):
        """jit(shard_map) per LSM level count (the runs tuple is part of
        the program signature)."""
        fn = self._chunk_fn_cache.get(n_runs)
        if fn is None:
            spec = P(AXIS)
            fn = jax.jit(
                _shard_map(
                    self._chunk_step,
                    mesh=self.mesh,
                    in_specs=(spec,) * 11 + (P(), P(), spec) + (spec,) * n_runs,
                    out_specs=(spec,) * 10,
                    **_SHARD_MAP_KW,
                ),
                donate_argnums=self.CHUNK_DONATE,
            )
            self._chunk_fn_cache[n_runs] = fn
        return fn

    def _get_timeline_fns(self, n_runs: int):
        """The sampled-wave (--timeline) programs: the SAME stage bodies
        as the fused chunk program, dispatched as three shard_maps —
        pre (expand..route), exchange (the all-to-all pair), post
        (dedup..stats) — so the host can block_until_ready between them
        and attribute real seconds per stage. The loop-carried buffers
        donate exactly as in the fused program (memo in pre; the nine
        state carries in post; the routed payloads through exchange):
        without donation every sampled chunk copies the capacity-shaped
        frontier/journal buffers through the stage outputs, which
        dominates the sampled wave on big geometries. The wave loop
        rebinds every donated carry from the stage returns. The cached
        occ array and the LSM runs stay undonated (reused across
        chunks), as does the frontier (read-only within a wave)."""
        spec = P(AXIS)
        if self._tl_pre_ex is None:
            def pre_step(frontier, fcount, memo, cursor, base_lgid):
                sp, sf, memo2, cg, ps = self._cs_pre(
                    frontier[0], fcount[0, 0], memo[0], cursor,
                    base_lgid[0, 0],
                )
                return sp[None], sf[None], memo2[None], cg[None], ps[None]

            def ex_step(send_pay, send_fps):
                rp = lax.all_to_all(send_pay[0], AXIS, 0, 0, tiled=True)
                rf = lax.all_to_all(send_fps[0], AXIS, 0, 0, tiled=True)
                return rp[None], rf[None]

            self._tl_pre_ex = (
                jax.jit(_shard_map(
                    pre_step, mesh=self.mesh,
                    in_specs=(spec, spec, spec, P(), spec),
                    out_specs=(spec,) * 5, **_SHARD_MAP_KW,
                ), donate_argnums=self.TL_DONATE["pre"]),
                jax.jit(_shard_map(
                    ex_step, mesh=self.mesh,
                    in_specs=(spec, spec), out_specs=(spec, spec),
                    **_SHARD_MAP_KW,
                ), donate_argnums=self.TL_DONATE["exchange"]),
            )
        post_fn = self._tl_post_cache.get(n_runs)
        if post_fn is None:
            def post_step(
                recv_pay, recv_fps, next_buf, jps, jpl, jcand, jfp,
                viol, stats, cov, cov_gen, pre_stats, occ, *runs,
            ):
                out = self._cs_post(
                    recv_pay[0], recv_fps[0], next_buf[0], jps[0],
                    jpl[0], jcand[0], jfp[0], viol[0], stats[0], cov[0],
                    cov_gen[0], pre_stats[0], occ, [r[0] for r in runs],
                )
                return tuple(x[None] for x in out)

            # donated: next_buf, jps, jpl, jcand, jfp, viol, stats, cov
            # (recv_pay/recv_fps can't alias the outputs; occ and the
            # LSM runs are reused across chunks)
            post_fn = jax.jit(_shard_map(
                post_step, mesh=self.mesh,
                in_specs=(spec,) * 12 + (P(),) + (spec,) * n_runs,
                out_specs=(spec,) * 9, **_SHARD_MAP_KW,
            ), donate_argnums=self.TL_DONATE["post"])
            self._tl_post_cache[n_runs] = post_fn
        return self._tl_pre_ex[0], self._tl_pre_ex[1], post_fn

    # ---------------- static audit surface ----------------

    def audit_programs(self):
        """Every device program a sharded run dispatches, as audit
        entries for the static donation auditor (analysis/donation.py) —
        the same entry schema as ``DeviceBFS.audit_programs``: ``fn`` is
        a ``.lower()``-able jitted callable (the production jit object),
        ``args`` its abstract arguments, ``carries``/``pinned`` the
        independent {argnum: name} donation declarations the auditor
        compares against the lowered aliasing, ``site`` a (file, line)
        anchor, ``per_wave`` the dispatch count per wave. Nothing is
        lowered or executed here; the ``carries`` maps are deliberately
        written out separately from ``CHUNK_DONATE``/``TL_DONATE`` so a
        dropped donate argnum diverges the two."""
        import inspect as _inspect

        sds = jax.ShapeDtypeStruct
        D, W = self.D, self.W
        n_runs = len(self._lsm.runs)
        i32s = sds((), np.int32)
        frontier = sds((D, self.FCAP + self.EPAD, W), jnp.int32)
        next_buf = sds((D, self.FCAP + self.EPAD, W), jnp.int32)
        fc = sds((D, 1), jnp.int32)
        bl = sds((D, 1), jnp.int32)
        jps = sds((D, self.JCAP + self.EPAD), jnp.int32)
        jpl = sds((D, self.JCAP + self.EPAD), jnp.int32)
        jcand = sds((D, self.JCAP + self.EPAD), jnp.int32)
        jfp = sds((D, self.JCAP + self.EPAD), jnp.uint64)
        viol = sds((D, max(1, len(self.invariants))), jnp.int32)
        stats = sds((D, 7), jnp.int64)
        memo = sds((D, self.MCAP, 2), jnp.uint64)
        cov = sds((D, self.n_actions, 3), jnp.int64)
        occ = sds((n_runs,), jnp.bool_)
        runs = tuple(
            sds((D, self._lsm.lv_size(i)), jnp.uint64)
            for i in range(n_runs)
        )

        def site(fn):
            f = _inspect.unwrap(fn)
            return (__file__, _inspect.getsourcelines(f)[1])

        yield {
            "name": "chunk", "fn": self._get_chunk_fn(n_runs),
            "args": (frontier, fc, next_buf, jps, jpl, jcand, jfp, viol,
                     stats, memo, cov, i32s, occ, bl, *runs),
            "carries": {2: "next_buf", 3: "jps", 4: "jpl", 5: "jcand",
                        6: "jfp", 7: "viol", 8: "stats", 9: "memo",
                        10: "cov"},
            "pinned": {0: "frontier"},
            "site": site(self._chunk_step), "per_wave": 1,
        }

        # --timeline stage programs: chain abstract shapes through the
        # jitted stages with eval_shape (free — no lowering happens
        # until the auditor lowers an entry it chose to audit)
        pre_fn, ex_fn, post_fn = self._get_timeline_fns(n_runs)
        pre_out = jax.eval_shape(pre_fn, frontier, fc, memo, i32s, bl)
        send_pay, send_fps, _memo2, cov_gen, pre_stats = pre_out
        ex_out = jax.eval_shape(ex_fn, send_pay, send_fps)
        recv_pay, recv_fps = ex_out
        yield {
            "name": "tl:pre", "fn": pre_fn,
            "args": (frontier, fc, memo, i32s, bl),
            "carries": {2: "memo"}, "pinned": {0: "frontier"},
            "site": site(self._cs_pre), "per_wave": 1,
        }
        yield {
            "name": "tl:exchange", "fn": ex_fn,
            "args": (send_pay, send_fps),
            "carries": {0: "send_pay", 1: "send_fps"}, "pinned": {},
            "site": site(self._get_timeline_fns), "per_wave": 1,
        }
        yield {
            "name": "tl:post", "fn": post_fn,
            "args": (recv_pay, recv_fps, next_buf, jps, jpl, jcand, jfp,
                     viol, stats, cov, cov_gen, pre_stats, occ, *runs),
            "carries": {2: "next_buf", 3: "jps", 4: "jpl", 5: "jcand",
                        6: "jfp", 7: "viol", 8: "stats", 9: "cov"},
            "pinned": {},
            "site": site(self._cs_post), "per_wave": 1,
        }

    def _chunk_step(
        self, frontier, fcount, next_buf, jps, jpl, jcand, jfp, viol, stats,
        memo, cov, cursor, occ, base_lgid, *runs,
    ):
        """One chunk of the current wave on one chip.

        frontier [1,F+EPAD,W]; fcount/base_lgid [1,1]; next_buf
        [1,F+EPAD,W]; jps/jpl/jcand [1,JC+EPAD] (the EPAD=D*RC tail rows
        are the emit drop region); jfp [1,JC+EPAD] u64 — each journal
        row's canonical fingerprint, the lane that makes the checkpoint
        mesh-portable (reshard routes rows by jfp mod D_new) and the
        wave-start LSM subtraction exact; viol [1,K]; occ bool[L]
        (replicated);
        runs: L sharded [1,lanes] sorted u64; memo [1,MCAP,2] shard-local
        canon memo; cov [1,n_actions,3] i64 per-shard cumulative
        [enabled, fired, new] per action rank (enabled/fired tally on the
        GENERATING chip, new on the OWNER chip after the all-to-all);
        stats [1,S] i64 = [wave new, jcount, cum generated,
        cum terminal, ovf bits, routed lanes, cum canon memo hits].
        Returns (+ new_run [1,R0]).
        """
        # strip the leading local-block axis shard_map hands us
        frontier, fcount, base_lgid = frontier[0], fcount[0, 0], base_lgid[0, 0]
        next_buf = next_buf[0]
        jps, jpl, jcand, viol, stats = jps[0], jpl[0], jcand[0], viol[0], stats[0]
        jfp = jfp[0]
        memo = memo[0]
        cov = cov[0]
        runs = [r[0] for r in runs]
        # composed from the same stage bodies the sampled --timeline
        # waves dispatch separately (integer-only wave math, so the
        # fused and staged programs are bit-identical — parity-gated by
        # tests/test_obs.py)
        send_pay, send_fps, memo, cov_gen, pre_stats = self._cs_pre(
            frontier, fcount, memo, cursor, base_lgid
        )
        # 5. ICI all-to-all: block d of my send goes to chip d; received
        # block d came from chip d (=> parent shard = recv row // RC)
        recv_pay = lax.all_to_all(send_pay, AXIS, 0, 0, tiled=True)
        recv_fps = lax.all_to_all(send_fps, AXIS, 0, 0, tiled=True)
        (next_buf, jps, jpl, jcand, jfp, viol, stats, cov, new_run,
         ) = self._cs_post(
            recv_pay, recv_fps, next_buf, jps, jpl, jcand, jfp, viol,
            stats, cov, cov_gen, pre_stats, occ, runs,
        )
        return (
            next_buf[None], jps[None], jpl[None], jcand[None], jfp[None],
            viol[None], stats[None], memo[None], cov[None], new_run[None],
        )

    def _cs_pre(self, frontier, fcount, memo, cursor, base_lgid):
        """Per-chip pre-exchange stages of one chunk (steps 1-4): expand,
        compact, canon, owner routing. Returns the all-to-all send blocks
        plus everything the post stage needs: ``cov_gen`` [K,2] =
        per-action [enabled, fired] tallied on the generating chip
        ([1,2] zeros when the model has no action ranks) and
        ``pre_stats`` [5] i64 = [n_gen, terminal, pre-exchange ovf bits
        (1=msg 2=valid 4=route), routed lanes, canon memo hits]."""
        model, D, A, W = self.model, self.D, self.A, self.W
        C, VC, RC = self.chunk, self.VC, self.RC
        K = self.n_actions

        # 1. expand `chunk` rows starting at the wave cursor
        batch = lax.dynamic_slice(frontier, (cursor, jnp.int32(0)), (C, W))
        live = (jnp.arange(C, dtype=jnp.int32) + cursor) < fcount
        if self._sparse:
            # guard pass: valid/rank/ovf only — no W-wide successor
            # rows (DCE-derived from _expand1, bit-identical)
            valid, rank, ovf = jax.vmap(model.guards1)(batch)
        else:
            succs, valid, rank, ovf = jax.vmap(model._expand1)(batch)
        valid = valid & live[:, None]
        expand_ovf = jnp.any(valid & ovf)
        n_gen = jnp.sum(valid)
        term = jnp.sum(live & ~jnp.any(valid, axis=1))

        # 1b. enabled/fired per action rank, tallied where the lanes are
        # generated (numpy mirror in checker/bfs.py; invalid lanes route
        # to drop bucket K)
        if K:
            rk = jnp.where(valid, rank, K)
            fired_k = jax.ops.segment_sum(
                jnp.ones((C * A,), jnp.int64), rk.reshape(-1),
                num_segments=K + 1,
            )[:K]
            en = (rank[:, :, None] == jnp.arange(K, dtype=rank.dtype)) & (
                valid[:, :, None]
            )  # [C, A, K] one-hot (compare beats a scatter on TPU)
            enabled_k = jnp.sum(jnp.any(en, axis=1), axis=0, dtype=jnp.int64)

        # 2. compact the valid lanes (sel[j] = flat lane of the j-th valid)
        vflat = valid.reshape(-1)
        vpos = jnp.cumsum(vflat) - 1
        compact_ovf = n_gen > VC
        sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
        sel = (
            jnp.full((VC + 1,), C * A, jnp.int32)
            .at[sdst]
            .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
        )
        selv = sel < C * A
        if self._sparse:
            # apply pass over the compacted worklist only; budget
            # overflow folds into the compaction bit (same remedy:
            # raise the static budget knob)
            flatc, apply_ovf = model.sparse_apply(batch, sel, selv, self._plan)
            compact_ovf = compact_ovf | apply_ovf
        else:
            flatp = jnp.concatenate(
                [succs.reshape(C * A, W), jnp.zeros((1, W), jnp.int32)],
                axis=0,
            )
            flatc = flatp[sel]  # [VC, W]
        parent_lgid = base_lgid + cursor + sel // A
        cand = sel % A

        # 3. canonical fingerprints on the compacted lanes — memoized on
        # the GENERATING chip (raw keys are shard-local; the all-to-all
        # below only ever moves canonical fingerprints)
        if self._use_memo:
            fps, memo, n_memo_hit = self.canon.fingerprints_memo(
                flatc, selv, memo
            )
        else:
            fps = self.canon._fingerprints(flatc)
            fps = jnp.where(selv, fps, U64_MAX)
            n_memo_hit = jnp.asarray(0, jnp.int32)

        # 4. route to owner chip = fp mod D: sort by owner, positional
        # slots. The action rank rides the payload so the OWNER chip can
        # attribute new-distinct states per action after dedup.
        lane_rank = jnp.concatenate(
            [rank.reshape(-1), jnp.full((1,), -1, rank.dtype)]
        )[sel]  # [VC] rank per compacted lane (drop row -> -1)
        payload = jnp.concatenate(
            [flatc, parent_lgid[:, None], cand[:, None],
             lane_rank[:, None].astype(jnp.int32)], axis=1
        )  # [VC, W+3] i32
        # fp mod D in u32 pieces (u64 div/mod lanes are slow on this TPU):
        # (hi*2^32 + lo) % D == ((hi%D) * (2^32%D) + lo%D) % D
        # exact only while (D-1)*(2^32%D) + (D-1) fits u32 — enforced at
        # construction (D <= 2^16), and real meshes are far smaller
        fhi, flo = split_u64(fps)
        t32 = np.uint32((1 << 32) % D)
        owner = (((fhi % np.uint32(D)) * t32 + flo % np.uint32(D))
                 % np.uint32(D)).astype(jnp.int32)
        owner = jnp.where(eq_u64(fps, U64_MAX), D, owner)  # invalid -> drop
        order = jnp.argsort(owner, stable=True)
        owner_s = owner[order]
        fps_s = fps[order]
        start = jnp.searchsorted(owner_s, jnp.arange(D + 1), side="left")
        pos_in_owner = jnp.arange(VC) - start[owner_s]
        ok = (owner_s < D) & (pos_in_owner < RC)
        route_ovf = jnp.any((owner_s < D) & (pos_in_owner >= RC))
        n_routed = jnp.sum(ok)
        slot = jnp.where(ok, owner_s * RC + pos_in_owner, D * RC)
        send_pay = jnp.zeros((D * RC + 1, W + 3), jnp.int32).at[slot].set(payload[order])[:-1]
        send_fps = jnp.full((D * RC + 1,), U64_MAX, jnp.uint64).at[slot].set(
            jnp.where(ok, fps_s, U64_MAX))[:-1]

        pre_stats = jnp.stack([
            n_gen.astype(jnp.int64),
            term.astype(jnp.int64),
            expand_ovf.astype(jnp.int64)
            + 2 * compact_ovf.astype(jnp.int64)
            + 4 * route_ovf.astype(jnp.int64),
            n_routed.astype(jnp.int64),
            n_memo_hit.astype(jnp.int64),
        ])
        cov_gen = (
            jnp.stack([enabled_k, fired_k], axis=1)
            if K else jnp.zeros((1, 2), jnp.int64)
        )
        return send_pay, send_fps, memo, cov_gen, pre_stats

    def _cs_post(
        self, recv_pay, recv_fps, next_buf, jps, jpl, jcand, jfp, viol,
        stats, cov, cov_gen, pre_stats, occ, runs,
    ):
        """Per-chip post-exchange stages of one chunk (steps 6-8): local
        dedup against the LSM runs, emit-append, owner-side coverage,
        invariants, stats fold. ``cov_gen``/``pre_stats`` carry the
        generating-chip tallies from ``_cs_pre``."""
        model, D, W = self.model, self.D, self.W
        RC = self.RC
        F, JC = self.FCAP, self.JCAP
        K = self.n_actions

        # 6. local dedup: probe the occupied LSM runs + first-occurrence
        rf, sidx = sort_u64_with_idx(recv_fps)
        uniq = jnp.ones_like(rf, dtype=bool).at[1:].set(ne_u64(rf[1:], rf[:-1]))
        fresh = uniq & ne_u64(rf, U64_MAX)
        for i, r in enumerate(runs):
            hit = lax.cond(
                occ[i],
                lambda rr: _probe(rr, rf),
                # rf != rf: an all-False array that carries the same
                # varying-manual-axes type as the true branch (a plain
                # jnp.zeros is unvarying and cond rejects the mismatch)
                lambda rr: rf != rf,
                r,
            )
            fresh = fresh & ~hit
        new = fresh
        n_new = jnp.sum(new)

        # 7. emit survivors: compact to a dense prefix of a [D*RC, W]
        # block, then ONE dynamic_update_slice per buffer appends at the
        # running cursor (rows [F, F+D*RC) / [JC, JC+D*RC) are the drop
        # region — checker/util.py emit_append; same redesign as
        # DeviceBFS._chunk_step step 5, retiring full-capacity scatters)
        ncount = stats[0].astype(jnp.int32)
        jcount = stats[1].astype(jnp.int32)
        npos = (jnp.cumsum(new) - 1).astype(jnp.int32)
        states_s = recv_pay[sidx, :W]
        B = D * RC
        esel = dense_prefix_sel(new, npos, B)
        blk = jnp.concatenate(
            [states_s, jnp.zeros((1, W), jnp.int32)], axis=0
        )[esel]
        jps_blk = jnp.concatenate(
            [(sidx // RC).astype(jnp.int32), jnp.zeros((1,), jnp.int32)]
        )[esel]
        jpl_blk = jnp.concatenate(
            [recv_pay[sidx, W], jnp.zeros((1,), jnp.int32)]
        )[esel]
        jc_blk = jnp.concatenate(
            [recv_pay[sidx, W + 1], jnp.zeros((1,), jnp.int32)]
        )[esel]
        jfp_blk = jnp.concatenate(
            [rf, jnp.full((1,), U64_MAX, jnp.uint64)]
        )[esel]
        next_buf, frontier_ovf = emit_append(next_buf, blk, ncount, n_new, F)
        jps, journal_ovf = emit_append(jps, jps_blk, jcount, n_new, JC)
        jpl, _ = emit_append(jpl, jpl_blk, jcount, n_new, JC)
        jcand, _ = emit_append(jcand, jc_blk, jcount, n_new, JC)
        jfp, _ = emit_append(jfp, jfp_blk, jcount, n_new, JC)
        if K:
            # new-distinct per rank on the owner chip (non-new lanes ->
            # drop bucket K; their routed rank column may be garbage 0s
            # from unfilled send slots, but `new` masks them out)
            recv_rank = recv_pay[sidx, W + 2]
            new_k = jax.ops.segment_sum(
                new.astype(jnp.int64), jnp.where(new, recv_rank, K),
                num_segments=K + 1,
            )[:K]
            cov = cov + jnp.concatenate(
                [cov_gen, new_k[:, None]], axis=1)
        # the chip's new fps as one sorted run (LSM level-0 insert)
        new_run = sort_u64(jnp.where(new, rf, U64_MAX))
        DRC = new_run.shape[0]
        if self.R0 > DRC:
            new_run = jnp.concatenate(
                [new_run, jnp.full((self.R0 - DRC,), U64_MAX, jnp.uint64)]
            )

        # 8. invariants on the received candidates; fold first-bad jidx
        jidx = jnp.where(new, jcount + npos, I32_MAX)
        for k, name in enumerate(self.invariants):
            okv = model.invariants[name](states_s)
            bad = new & ~okv
            viol = viol.at[k].min(jnp.min(jnp.where(bad, jidx, I32_MAX)))

        ovf_bits = (
            pre_stats[2]
            + 8 * frontier_ovf.astype(jnp.int64)
            + 16 * journal_ovf.astype(jnp.int64)
        )
        stats = jnp.stack(
            [
                stats[0] + n_new,
                stats[1] + n_new,
                stats[2] + pre_stats[0],
                stats[3] + pre_stats[1],
                stats[4] | ovf_bits,
                stats[5] + pre_stats[3],
                stats[6] + pre_stats[4],
            ]
        )
        return next_buf, jps, jpl, jcand, jfp, viol, stats, cov, new_run

    # ---------------- capacity growth (between waves, host-mediated) ------

    def _maybe_grow(self, state, fcounts, jcounts):
        """Host-side: fetch, pad, re-place any buffer the next wave could
        outgrow. Rare (4x growth), so the host round-trip is acceptable;
        the jitted programs retrace automatically at the new shapes. The
        seen-set needs no growth — LSM levels appear on demand."""
        ncount = int(fcounts.max())
        jc = int(jcounts.max())
        D, W = self.D, self.W

        def repad(key, new_rows, old_rows, fill, cols=None):
            h = np.asarray(jax.device_get(state[key]))
            shape = (D, new_rows) if cols is None else (D, new_rows, cols)
            out = np.full(shape, fill, dtype=h.dtype)
            out[:, :old_rows] = h
            state[key] = jax.device_put(out, self._sharding)

        if ncount * self.HEADROOM > self.FCAP and self.FCAP < self.MAX_FCAP:
            new = _next_cap(ncount * self.HEADROOM, self.FCAP, self.MAX_FCAP,
                            self.GROWTH, self.chunk)
            repad("frontier", new + self.EPAD, self.FCAP + self.EPAD, 0, cols=W)
            state["next_buf"] = jax.device_put(
                np.zeros((D, new + self.EPAD, W), np.int32), self._sharding)
            self.FCAP = new
        if jc + ncount * self.HEADROOM > self.JCAP and self.JCAP < self.MAX_JCAP:
            new = _next_cap(jc + ncount * self.HEADROOM, self.JCAP,
                            self.MAX_JCAP, self.GROWTH, 1)
            for key in ("jps", "jpl", "jcand"):
                repad(key, new + self.EPAD, self.JCAP + self.EPAD, 0)
            repad("jfp", new + self.EPAD, self.JCAP + self.EPAD,
                  np.uint64(U64_MAX))
            self.JCAP = new
        return state

    def grow_for_overflow(self, bits: int) -> dict | None:
        """Constructor-kwarg overrides that would clear the overflow
        bits on a rebuilt engine, or None if no growth can help (the
        supervisor then reports the failure as unrecoverable). Mirrors
        DeviceBFS.grow_for_overflow; route_cap is the sharded-only knob."""
        bits = int(bits)
        if bits & 1:
            return None  # msg-slot width is a model property, not a cap
        growth: dict = {}
        if bits & 2:
            vps = min(self.A, -(-self.VC // self.chunk) * 2)
            growth["valid_per_state"] = vps
            growth["valid_per_group"] = None
        if bits & 4:
            growth["route_cap"] = self.RC * 2
        if bits & 8:
            growth["frontier_cap"] = self.FCAP * 2
            growth["max_frontier_cap"] = max(self.MAX_FCAP, self.FCAP * 4)
        if bits & 16:
            growth["journal_cap"] = self.JCAP * 2
            growth["max_journal_cap"] = max(self.MAX_JCAP, self.JCAP * 4)
        if bits & self.SEEN_OVF_BIT:
            growth["max_seen_cap"] = self.MAX_SCAP * 4
        return growth or None

    def survivors_for_shard_loss(self, shard: int) -> dict | None:
        """Constructor-kwarg overrides that rebuild this engine on the
        mesh minus the lost shard's device, or None when there is no
        surviving mesh (D == 1). The supervisor pairs this with a
        reshard-on-resume of the newest checkpoint."""
        devs = list(self.mesh.devices.flat)
        if len(devs) <= 1:
            return None
        devs.pop(int(shard) % len(devs))
        return {"devices": devs}

    def _rebuild(self, overrides: dict) -> "ShardedBFS":
        """A fresh engine with this one's constructor kwargs plus
        ``overrides`` (the supervisor's growth / shrunk-mesh dicts)."""
        return type(self)(**{**self._ctor_kw, **overrides})

    # ---------------- checkpoint ----------------

    def _ckpt_ident(self) -> str:
        # hashv=5: k-round 1-WL refinement (ops/symmetry.py) changed the
        # canonical representative of signature-tied states; the
        # refinement depth is part of the fingerprint formula. The canon
        # memo is value-preserving and not part of the identity.
        # /D=<n>/ is PROVENANCE, not identity: resilience/ckpt.check_spec
        # strips it (mesh_neutral) when deciding reshardability, and the
        # resume path re-routes the payload when it differs.
        wl = getattr(self.canon, "refine_rounds", 1)
        return (
            f"sharded/{self.model.name}/{self.model.p}/W={self.W}"
            f"/D={self.D}/sym={self.canon.symmetry}/hashv=5/wl={wl}"
            f"/inv={','.join(self.invariants)}"
        )

    def _save_checkpoint(
        self, path, state, fcounts, scounts, jcounts, n0, base_lgid,
        distinct, total, terminal, depth, gen_prev, routed_prev, depth_counts,
        coverage, seen_override=None,
    ):
        # seen_override: wave-start per-shard fingerprints computed by
        # _wave_start_seen when the LSM is contaminated by an aborted
        # wave (overflow / shard-loss abort paths)
        seen = self._lsm_export() if seen_override is None else seen_override
        assert [len(s) for s in seen] == [int(x) for x in scounts], (
            "LSM export does not match per-shard scounts"
        )
        fmax = int(fcounts.max())
        jmax = int(jcounts.max())
        smax = max((len(s) for s in seen), default=0)
        seen_h = np.full((self.D, smax), np.uint64(U64_MAX))
        for d, s in enumerate(seen):
            seen_h[d, : len(s)] = s
        frontier_h = np.asarray(jax.device_get(state["frontier"]))[:, :fmax]
        # crash-safe write (resilience/ckpt.py): tmp + fsync + rename,
        # content hash + format version, generation rotation
        rckpt.save_npz(
            path,
            dict(
                # payload layout v2: + jfp (per-row journal fingerprints,
                # the mesh-portability lane). v1 payloads still load —
                # _recover_journal_fps rebuilds jfp by replay.
                version=2,
                spec=self._ckpt_ident(),
                fcounts=fcounts, scounts=scounts, jcounts=jcounts,
                n0=n0, base_lgid=base_lgid,
                frontier=frontier_h,
                seen=seen_h,
                jps=np.asarray(jax.device_get(state["jps"]))[:, :jmax],
                jpl=np.asarray(jax.device_get(state["jpl"]))[:, :jmax],
                jcand=np.asarray(jax.device_get(state["jcand"]))[:, :jmax],
                jfp=np.asarray(jax.device_get(state["jfp"]))[:, :jmax],
                init_by_shard_flat=np.concatenate(
                    [np.stack(s) if s else np.zeros((0, self.W), np.int32)
                     for s in self._init_by_shard], axis=0),
                init_by_shard_count=np.asarray(
                    [len(s) for s in self._init_by_shard], np.int64),
                distinct=distinct, total=total, terminal=terminal,
                depth=depth,
                gen_prev=gen_prev, routed_prev=routed_prev,
                depth_counts=np.asarray(depth_counts, dtype=np.int64),
                coverage=np.asarray(coverage, dtype=np.int64),
            ),
            keep=getattr(self, "_ckpt_keep", rckpt.DEFAULT_KEEP),
            chaos=getattr(self, "_chaos", None),
        )

    # ------------- mesh portability (reshard / recovery) -------------

    def _wave_start_seen(self, state, stats_h, jcounts, scounts, ovf_bits):
        """Per-shard wave-start seen fingerprints at an abort point, or
        None when they cannot be reconstructed.

        The chunk loop inserts each chunk's new fingerprints into the
        LSM as it goes, so by the time an abort fires the seen-set is
        contaminated with the (partial) aborted wave. But the SAME
        chunk programs journalled those fingerprints into the jfp lane:
        rows [jcounts[d], stats_h[d,1]) are exactly the wave's inserts,
        so subtracting them from the LSM export recovers the wave-start
        set bit-exactly. Fallback chain when lanes overflowed:

          journal intact (bit 16 clear) -> jfp slice (exact);
          journal full but frontier intact (bit 8 clear) -> refingerprint
            next_buf rows [0, stats_h[d,0]) (the same states, undropped);
          both overflowed -> None (some inserted fps are unrecorded).

        Every reconstruction is length-verified against the wave-start
        scounts before use — a mismatch returns None rather than an
        unsound checkpoint.
        """
        D = self.D
        stats_h = np.asarray(stats_h)
        lsm = self._lsm_export()  # wave-start seen + aborted wave's inserts
        if not (ovf_bits & 16):
            jfp_h = np.asarray(jax.device_get(state["jfp"]))
            wave = [
                jfp_h[d, int(jcounts[d]): int(stats_h[d, 1])].astype(np.uint64)
                for d in range(D)
            ]
        elif not (ovf_bits & 8):
            nb = np.asarray(jax.device_get(state["next_buf"]))
            wave = []
            for d in range(D):
                rows = nb[d, : int(stats_h[d, 0])]
                wave.append(
                    np.asarray(
                        jax.device_get(self.canon.fingerprints(rows)),
                        dtype=np.uint64,
                    )
                    if len(rows)
                    else np.zeros(0, np.uint64)
                )
        else:
            return None
        out = []
        for d in range(D):
            ws = np.setdiff1d(lsm[d], wave[d])
            if len(ws) != int(scounts[d]):
                return None
            out.append(ws)
        return out

    def _abort_wave_start(
        self, checkpoint_path, state, stats_h, fcounts, scounts, jcounts,
        n0, base_lgid, distinct, total, terminal, depth, gen_prev,
        routed_prev, depth_counts, cov_hd,
    ):
        """Spill a wave-start checkpoint at an abort point (overflow,
        shard loss, stall). All counters passed in are the HOST wave-
        start values — the journal/jfp tails the aborted wave appended
        are sliced off by _save_checkpoint's jmax, and the seen-set is
        rebuilt by _wave_start_seen. Returns True when a checkpoint was
        written (False: no path routed, or the wave is unreconstructable
        because both the journal and frontier lanes overflowed)."""
        if checkpoint_path is None:
            return False
        stats_h = np.asarray(stats_h)
        ovf_bits = int(np.bitwise_or.reduce(stats_h[:, 4]))
        ws = self._wave_start_seen(state, stats_h, jcounts, scounts, ovf_bits)
        if ws is None:
            return False
        self._save_checkpoint(
            checkpoint_path, state, fcounts, scounts, jcounts, n0,
            base_lgid, distinct, total, terminal, depth, gen_prev,
            routed_prev, depth_counts, cov_hd, seen_override=ws,
        )
        return True

    def _recover_journal_fps(self, ck, d_ck) -> np.ndarray:
        """Rebuild the jfp lane of a pre-v2 (payload ``version=1``)
        checkpoint by topological replay.

        v1 payloads journalled (parent shard, parent lgid, cand) per row
        but not the row's own fingerprint. Every row's STATE is
        recomputable: replay the journalled candidate action on the
        parent state. Rows resolve in rounds — a row is ready once its
        parent is an init state (known immediately) or an already-
        resolved journal row — and each round batches all ready parents
        through one vmapped expansion + fingerprint call. Cost is one
        expansion per journalled state, paid once: the resumed run
        checkpoints in v2 format, so the upgrade never repeats.
        """
        model, W = self.model, self.W
        jcounts = np.asarray(ck["jcounts"], np.int64)
        n0 = np.asarray(ck["n0"], np.int64)
        jmax = int(jcounts.max()) if len(jcounts) else 0
        jfp = np.full((d_ck, jmax), np.uint64(U64_MAX))
        if jmax == 0:
            return jfp
        jps = np.asarray(ck["jps"])
        jpl = np.asarray(ck["jpl"])
        jcand = np.asarray(ck["jcand"])
        counts = np.asarray(ck["init_by_shard_count"], np.int64)
        flat = np.asarray(ck["init_by_shard_flat"])
        ioff = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        states = np.zeros((d_ck, jmax, W), np.int32)
        known = np.zeros((d_ck, jmax), bool)
        pending = [
            (d, j) for d in range(d_ck) for j in range(int(jcounts[d]))
        ]
        expand1 = jax.jit(jax.vmap(model._expand1))
        CH = 4096  # fixed batch: one compile, garbage-padded tail
        while pending:
            ready: list[tuple[int, int]] = []
            parents: list[np.ndarray] = []
            rest: list[tuple[int, int]] = []
            for d, j in pending:
                pd, pl = int(jps[d, j]), int(jpl[d, j])
                if pl < n0[pd]:
                    parents.append(flat[ioff[pd] + pl])
                elif known[pd, pl - n0[pd]]:
                    parents.append(states[pd, pl - n0[pd]])
                else:
                    rest.append((d, j))
                    continue
                ready.append((d, j))
            assert ready, "journal replay stuck: unresolvable parent row"
            batch = np.stack(parents).astype(np.int32)
            children = np.empty((len(ready), W), np.int32)
            for s in range(0, len(ready), CH):
                blk = batch[s: s + CH]
                pad = CH - len(blk)
                if pad:
                    blk = np.concatenate(
                        [blk, np.repeat(blk[:1], pad, axis=0)], axis=0)
                succs, _valid, _rank, _ovf = jax.device_get(expand1(blk))
                for i, (d, j) in enumerate(ready[s: s + CH]):
                    children[s + i] = succs[i, int(jcand[d, j])]
            fps = np.asarray(
                jax.device_get(self.canon.fingerprints(children)),
                dtype=np.uint64,
            )
            for i, (d, j) in enumerate(ready):
                states[d, j] = children[i]
                jfp[d, j] = fps[i]
                known[d, j] = True
            pending = rest
        return jfp

    def _reshard_payload(self, ck: dict, d_old: int) -> dict:
        """Re-route a mesh-portable checkpoint written on a D=``d_old``
        mesh onto this engine's D=``self.D`` mesh.

        Every persisted structure is a per-shard partition of one global
        set, keyed by fingerprint: seen fps and init states re-route by
        ``fp mod D_new`` directly; journal rows route by their jfp, kept
        in stable (old shard, old row) order per new shard EXCEPT that
        frontier rows (the last fcounts[d] rows of each old shard) are
        ordered LAST per new shard — preserving the engine invariant
        that frontier row i of shard d is journal row
        ``jcounts[d]-fcounts[d]+i``. Parent pointers rewrite through the
        old->new (shard, lgid) maps. Per-shard coverage counters sum
        into shard 0 (only fleet totals are ever reported). The result
        resumes with counts bit-identical to the same run on the
        original mesh.
        """
        D_new, W = self.D, self.W
        fcounts_o = np.asarray(ck["fcounts"], np.int64)
        scounts_o = np.asarray(ck["scounts"], np.int64)
        jcounts_o = np.asarray(ck["jcounts"], np.int64)
        frontier_o = np.asarray(ck["frontier"])
        seen_o = np.asarray(ck["seen"])
        jps_o, jpl_o = np.asarray(ck["jps"]), np.asarray(ck["jpl"])
        jcand_o = np.asarray(ck["jcand"])
        jfp_o = np.asarray(ck["jfp"], np.uint64)
        counts_o = np.asarray(ck["init_by_shard_count"], np.int64)
        flat = np.asarray(ck["init_by_shard_flat"]).astype(np.int32)
        n0_o = np.asarray(ck["n0"], np.int64)

        # --- inits: route by fingerprint, stable flat order per shard
        n_init = len(flat)
        if n_init:
            ifp = np.asarray(
                jax.device_get(self.canon.fingerprints(flat)), np.uint64)
        else:
            ifp = np.zeros(0, np.uint64)
        iowner = (ifp % np.uint64(D_new)).astype(np.int64)
        ioff_o = np.concatenate([[0], np.cumsum(counts_o)]).astype(np.int64)
        n0_n = np.bincount(iowner, minlength=D_new).astype(np.int64)
        iord = np.argsort(iowner, kind="stable")
        new_il = np.empty(n_init, np.int64)
        new_il[iord] = np.concatenate(
            [np.arange(int(c)) for c in n0_n]
        ) if n_init else np.zeros(0, np.int64)
        init_by_shard_n: list[list[np.ndarray]] = [[] for _ in range(D_new)]
        for idx in iord:
            init_by_shard_n[int(iowner[idx])].append(np.asarray(flat[idx]))

        # --- journal rows: flatten, route by jfp, frontier rows last
        nrows = int(jcounts_o.sum())
        glob_d = np.repeat(np.arange(d_old), jcounts_o)
        glob_j = (
            np.concatenate([np.arange(int(c)) for c in jcounts_o])
            if nrows else np.zeros(0, np.int64)
        ).astype(np.int64)
        joff_o = np.concatenate([[0], np.cumsum(jcounts_o)]).astype(np.int64)
        jfp_flat = (
            np.concatenate(
                [jfp_o[d, : int(jcounts_o[d])] for d in range(d_old)])
            if nrows else np.zeros(0, np.uint64)
        )
        jowner = (jfp_flat % np.uint64(D_new)).astype(np.int64)
        front0 = jcounts_o - fcounts_o  # first frontier journal row, per shard
        is_front = glob_j >= front0[glob_d]
        order = np.lexsort((glob_j, glob_d, is_front, jowner))
        jcounts_n = np.bincount(jowner, minlength=D_new).astype(np.int64)
        starts = np.concatenate([[0], np.cumsum(jcounts_n)]).astype(np.int64)
        # old flat row -> new (shard, row); `order` is grouped by owner
        new_jd = np.repeat(np.arange(D_new), jcounts_n)
        new_jj = (
            np.concatenate([np.arange(int(c)) for c in jcounts_n])
            if nrows else np.zeros(0, np.int64)
        ).astype(np.int64)
        jd_of = np.empty(nrows, np.int64)
        jj_of = np.empty(nrows, np.int64)
        jd_of[order] = new_jd
        jj_of[order] = new_jj

        # --- parent pointer rewrite through the old->new maps
        pd = (
            np.concatenate(
                [jps_o[d, : int(jcounts_o[d])] for d in range(d_old)])
            if nrows else np.zeros(0, np.int64)
        ).astype(np.int64)
        pl = (
            np.concatenate(
                [jpl_o[d, : int(jcounts_o[d])] for d in range(d_old)])
            if nrows else np.zeros(0, np.int64)
        ).astype(np.int64)
        cand_flat = (
            np.concatenate(
                [jcand_o[d, : int(jcounts_o[d])] for d in range(d_old)])
            if nrows else np.zeros(0, np.int64)
        )
        isin = pl < n0_o[pd]
        rew_pd = np.empty(nrows, np.int64)
        rew_pl = np.empty(nrows, np.int64)
        fi = ioff_o[pd[isin]] + pl[isin]
        rew_pd[isin] = iowner[fi]
        rew_pl[isin] = new_il[fi]
        fj = joff_o[pd[~isin]] + (pl[~isin] - n0_o[pd[~isin]])
        rew_pd[~isin] = jd_of[fj]
        rew_pl[~isin] = n0_n[jd_of[fj]] + jj_of[fj]

        jmax_n = int(jcounts_n.max()) if nrows else 0
        jps_n = np.zeros((D_new, jmax_n), np.int32)
        jpl_n = np.zeros((D_new, jmax_n), np.int32)
        jcand_n = np.zeros((D_new, jmax_n), np.int32)
        jfp_n = np.full((D_new, jmax_n), np.uint64(U64_MAX))
        rew_pd_s, rew_pl_s = rew_pd[order], rew_pl[order]
        cand_s, fp_s = cand_flat[order], jfp_flat[order]
        for d in range(D_new):
            s, c = int(starts[d]), int(jcounts_n[d])
            jps_n[d, :c] = rew_pd_s[s: s + c]
            jpl_n[d, :c] = rew_pl_s[s: s + c]
            jcand_n[d, :c] = cand_s[s: s + c]
            jfp_n[d, :c] = fp_s[s: s + c]

        # --- frontier: journal-tail rows in new-journal order (or the
        # inits themselves when no wave has committed yet)
        if nrows:
            isf_s = is_front[order]
            gd_s, gj_s = glob_d[order], glob_j[order]
            fcounts_n = np.bincount(
                jowner[is_front], minlength=D_new).astype(np.int64)
            fmax_n = max(1, int(fcounts_n.max()))
            frontier_n = np.zeros((D_new, fmax_n, W), np.int32)
            fpos = np.zeros(D_new, np.int64)
            for k in range(nrows):
                if not isf_s[k]:
                    continue
                d = int(new_jd[k])
                frontier_n[d, fpos[d]] = frontier_o[
                    gd_s[k], int(gj_s[k] - front0[gd_s[k]])]
                fpos[d] += 1
        else:
            fcounts_n = n0_n.copy()
            fmax_n = max(1, int(fcounts_n.max()))
            frontier_n = np.zeros((D_new, fmax_n, W), np.int32)
            for d in range(D_new):
                for i, st in enumerate(init_by_shard_n[d]):
                    frontier_n[d, i] = st

        # --- seen: repartition + sort per new shard
        seen_parts: list[list[np.ndarray]] = [[] for _ in range(D_new)]
        for d in range(d_old):
            s = seen_o[d, : int(scounts_o[d])].astype(np.uint64)
            own = (s % np.uint64(D_new)).astype(np.int64)
            for dn in range(D_new):
                seen_parts[dn].append(s[own == dn])
        seen_n = [
            np.sort(np.concatenate(p)) if p else np.zeros(0, np.uint64)
            for p in seen_parts
        ]
        scounts_n = np.asarray([len(s) for s in seen_n], np.int64)
        assert (scounts_n == n0_n + jcounts_n).all(), (
            "reshard broke the seen = inits + journal invariant"
        )
        smax_n = max(1, int(scounts_n.max()))
        seen_h = np.full((D_new, smax_n), np.uint64(U64_MAX))
        for d, s in enumerate(seen_n):
            seen_h[d, : len(s)] = s

        cov_o = (
            np.asarray(ck["coverage"], np.int64)
            if "coverage" in ck
            else np.zeros((d_old, self.n_actions, 3), np.int64)
        )
        cov_n = np.zeros((D_new, self.n_actions, 3), np.int64)
        if self.n_actions:
            cov_n[0] = cov_o.sum(axis=0)

        out = dict(ck)
        out.update(
            version=np.int64(2),
            spec=self._ckpt_ident(),
            fcounts=fcounts_n, scounts=scounts_n, jcounts=jcounts_n,
            n0=n0_n, base_lgid=n0_n + jcounts_n - fcounts_n,
            frontier=frontier_n, seen=seen_h,
            jps=jps_n, jpl=jpl_n, jcand=jcand_n, jfp=jfp_n,
            init_by_shard_flat=np.concatenate(
                [np.stack(s) if s else np.zeros((0, W), np.int32)
                 for s in init_by_shard_n], axis=0),
            init_by_shard_count=np.asarray(
                [len(s) for s in init_by_shard_n], np.int64),
            coverage=cov_n,
        )
        return out

    # ---------------- host driver ----------------

    def run(
        self,
        max_depth: int | None = None,
        verbose: bool = False,
        time_budget_s: float | None = None,
        collect_metrics: bool = False,
        checkpoint_path: str | None = None,
        checkpoint_every_s: float = 300.0,
        checkpoint_keep: int = rckpt.DEFAULT_KEEP,
        resume: str | None = None,
        reshard: bool = True,
        stall_abort_factor: float | None = None,
        telemetry=None,
        preempt=None,
        chaos=None,
    ) -> ShardedResult:
        model, D, W, C = self.model, self.D, self.W, self.chunk
        t0 = time.perf_counter()
        exhausted = True
        exit_cause = None
        self._ckpt_keep = checkpoint_keep
        self._chaos = chaos
        # telemetry rides the once-per-wave stats fetch the loop already
        # does — zero extra collectives or device syncs
        tel = telemetry if telemetry is not None else NULL_TELEMETRY

        init = np.asarray(model.init_states())
        init_fps = np.asarray(
            jax.device_get(self.canon.fingerprints(init)), dtype=np.uint64)
        # dedup inits (first occurrence wins)
        order = np.argsort(init_fps, kind="stable")
        keep = np.ones(len(order), dtype=bool)
        sf = init_fps[order]
        dupm = np.zeros(len(order), dtype=bool)
        dupm[1:] = sf[1:] == sf[:-1]
        keep[order[dupm]] = False
        init_d, init_fps = init[keep], init_fps[keep]

        violation = None
        viol_site = None  # (shard, lgid)
        init_trace = None  # one-entry trace for a depth-0 violation

        ck_gen = 0
        ck_skipped: list[str] = []
        reshard_from: int | None = None
        if resume is not None:
            ck, ck_gen, ck_skipped = rckpt.load_npz(
                resume, keep=checkpoint_keep)
            ident = self._ckpt_ident()
            rckpt.check_spec(ck, ident, resume, allow_reshard=reshard)
            d_ck = rckpt.mesh_d_of(str(ck["spec"])) or D
            if "jfp" not in ck:
                # pre-v2 payload: rebuild the fingerprint lane once by
                # replay — the resumed run saves in v2, so this upgrade
                # cost is paid a single time per lineage
                ck = dict(ck)
                ck["jfp"] = self._recover_journal_fps(ck, d_ck)
            if d_ck != D:
                ck = self._reshard_payload(ck, d_ck)
                reshard_from = d_ck
            fcounts = np.asarray(ck["fcounts"], np.int64)
            scounts = np.asarray(ck["scounts"], np.int64)
            jcounts = np.asarray(ck["jcounts"], np.int64)
            n0 = np.asarray(ck["n0"], np.int64)
            base_lgid = np.asarray(ck["base_lgid"], np.int64)
            fmax, jmax = int(fcounts.max()), int(jcounts.max())
            self.FCAP = _next_cap(max(self.FCAP, fmax * self.HEADROOM),
                                  self.FCAP, self.MAX_FCAP, self.GROWTH, self.chunk)
            self.JCAP = _next_cap(max(self.JCAP, jmax + fmax * self.HEADROOM),
                                  self.JCAP, self.MAX_JCAP, self.GROWTH, 1)
            frontier_h = np.zeros((D, self.FCAP + self.EPAD, W), np.int32)
            frontier_h[:, :fmax] = ck["frontier"]
            jh = {k: np.zeros((D, self.JCAP + self.EPAD), np.int32) for k in
                  ("jps", "jpl", "jcand")}
            for k in jh:
                jh[k][:, :jmax] = ck[k]
            jfp_h = np.full((D, self.JCAP + self.EPAD), np.uint64(U64_MAX))
            jfp_h[:, :jmax] = np.asarray(ck["jfp"], np.uint64)[:, :jmax]
            seen_h = np.asarray(ck["seen"])
            self._lsm_seed(
                [seen_h[d, : scounts[d]] for d in range(D)]
            )
            counts = np.asarray(ck["init_by_shard_count"])
            flat = np.asarray(ck["init_by_shard_flat"])
            self._init_by_shard = []
            off = 0
            for d in range(D):
                self._init_by_shard.append(
                    [flat[off + i] for i in range(int(counts[d]))])
                off += int(counts[d])
            distinct = int(ck["distinct"])
            total = int(ck["total"])
            terminal = int(ck["terminal"])
            depth = int(ck["depth"])
            gen_prev = int(ck["gen_prev"])
            routed_prev = int(ck["routed_prev"])
            depth_counts = [int(x) for x in ck["depth_counts"]]
            # pre-coverage checkpoints resume with zeroed counters
            cov_hd = (
                np.asarray(ck["coverage"], dtype=np.int64)
                if "coverage" in ck
                else np.zeros((D, self.n_actions, 3), np.int64)
            )
            # per-shard generated/terminal/routed cums are not persisted
            # per shard; resume them as deltas from zero and add the saved
            # totals back via the *_base offsets
            stats_h0 = np.zeros((D, 7), np.int64)
            stats_h0[:, 1] = jcounts
            gen_base, term_base, routed_base = gen_prev, terminal, routed_prev
            gen_prev = routed_prev = terminal = 0
            state = {
                "frontier": jax.device_put(frontier_h, self._sharding),
                "next_buf": jax.device_put(
                    np.zeros((D, self.FCAP + self.EPAD, W), np.int32),
                    self._sharding),
                "jps": jax.device_put(jh["jps"], self._sharding),
                "jpl": jax.device_put(jh["jpl"], self._sharding),
                "jcand": jax.device_put(jh["jcand"], self._sharding),
                "jfp": jax.device_put(jfp_h, self._sharding),
                "viol": jax.device_put(
                    np.full((D, max(1, len(self.invariants))), I32_MAX,
                            np.int32), self._sharding),
                "stats": jax.device_put(stats_h0, self._sharding),
            }
        else:
            frontier_h = np.zeros((D, self.FCAP + self.EPAD, W), np.int32)
            fcounts = np.zeros(D, np.int64)
            self._init_by_shard = [[] for _ in range(D)]
            per_shard_fps: list[list[int]] = [[] for _ in range(D)]
            for k in range(len(init_d)):
                d = int(init_fps[k] % D)
                frontier_h[d, fcounts[d]] = init_d[k]
                per_shard_fps[d].append(init_fps[k])
                self._init_by_shard[d].append(np.asarray(init_d[k]))
                fcounts[d] += 1
            self._lsm_seed(
                [np.asarray(a, np.uint64) for a in per_shard_fps]
            )
            scounts = fcounts.copy()
            jcounts = np.zeros(D, np.int64)
            n0 = fcounts.copy()  # per-shard init count (lgid < n0[d] => init)
            base_lgid = np.zeros(D, np.int64)
            gen_base = term_base = routed_base = 0

            viol_init = self._check_init(init_d)
            if viol_init is not None:
                violation, bad_idx = viol_init
                init_trace = [("Initial predicate", model.decode(init_d[bad_idx]))]

            state = {
                "frontier": jax.device_put(frontier_h, self._sharding),
                "next_buf": jax.device_put(
                    np.zeros((D, self.FCAP + self.EPAD, W), np.int32),
                    self._sharding),
                "jps": jax.device_put(
                    np.zeros((D, self.JCAP + self.EPAD), np.int32),
                    self._sharding),
                "jpl": jax.device_put(
                    np.zeros((D, self.JCAP + self.EPAD), np.int32),
                    self._sharding),
                "jcand": jax.device_put(
                    np.zeros((D, self.JCAP + self.EPAD), np.int32),
                    self._sharding),
                "jfp": jax.device_put(
                    np.full((D, self.JCAP + self.EPAD), np.uint64(U64_MAX)),
                    self._sharding),
                "viol": jax.device_put(
                    np.full((D, max(1, len(self.invariants))), I32_MAX, np.int32),
                    self._sharding),
                "stats": jax.device_put(
                    np.zeros((D, 7), np.int64), self._sharding),
            }
            distinct = int(len(init_d))
            total = int(len(init))  # pre-dedup, matching BFSChecker seeding
            terminal = 0
            gen_prev = 0
            routed_prev = 0
            depth = 0
            depth_counts = [distinct]
            cov_hd = np.zeros((D, self.n_actions, 3), np.int64)

        tel.open_run(self._telemetry_manifest())
        if resume is not None:
            if ck_skipped:
                tel.event(
                    "ckpt_generation", path=resume, generation=ck_gen,
                    skipped=list(ck_skipped))
            tel.event(
                "resume", path=resume, generation=ck_gen, depth=depth,
                distinct=distinct)
            if reshard_from is not None:
                tel.event(
                    "reshard", path=resume, from_d=reshard_from, to_d=D,
                    depth=depth, distinct=distinct)
        metrics: list[dict] | None = [] if collect_metrics else None
        last_ckpt = time.perf_counter()
        # fresh per-shard memo per run: a pure cache, but starting empty
        # keeps consecutive runs of one engine byte-reproducible
        state["memo"] = self._memo.reset()
        state["cov"] = jax.device_put(cov_hd, self._sharding)
        memo_prev = 0
        per_shard_memo = np.zeros(D, np.int64)
        wave_times: list[float] = []  # stall-watchdog rolling window
        # wave-timeline observatory (obs/): sampled waves dispatch the
        # pre/exchange/post programs separately (bit-identical math);
        # every wave gets the phase split + analytic HBM watermark
        tl_every = int(getattr(tel, "timeline_every", 0) or 0)
        tl_wave_s: list[float] = []
        fused_wave_s: list[float] = []
        memwatch = MemWatch(tel) if tel.active else None
        tel_s_last = 0.0
        routed_prev_d = np.zeros(D, np.int64)  # per-shard a2a cums

        while fcounts.sum() and violation is None:
            if preempt is not None and preempt.requested:
                # the final-save block below writes the (single)
                # wave-boundary checkpoint for this exit path
                exhausted = False
                exit_cause = "preempted"
                tel.event(
                    "preempt", signame=preempt.signame, depth=depth,
                    checkpoint=checkpoint_path)
                break
            if chaos is not None:
                chaos.wave_start(depth + 1)
            if max_depth is not None and depth >= max_depth:
                exhausted = False
                exit_cause = "max_depth"
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                exhausted = False
                exit_cause = "time_budget"
                break
            # top-absorb capacity guard, per chip (see DeviceBFS.run):
            # conservative — a chip's wave-new count is bounded by FCAP
            # and by the WHOLE mesh's routed candidates (fp%D routing can
            # send every chip's successors to one owner)
            worst = int(scounts.max()) + min(self.FCAP, int(fcounts.sum()) * self.VC)
            if worst > self.TOPSZ:
                if checkpoint_path is not None:
                    self._save_checkpoint(
                        checkpoint_path, state, fcounts, scounts,
                        jcounts, n0, base_lgid, distinct, total,
                        terminal + term_base, depth,
                        gen_prev + gen_base, routed_prev + routed_base,
                        depth_counts, cov_hd,
                    )
                raise CapacityOverflow(
                    "sharded seen-set capacity overflow; raise max_seen_cap",
                    what=("seen",), bits=self.SEEN_OVF_BIT,
                    checkpoint_saved=checkpoint_path is not None,
                )
            tw = time.perf_counter()
            fc_dev = jax.device_put(
                fcounts.astype(np.int32).reshape(D, 1), self._sharding)
            bl_dev = jax.device_put(
                base_lgid.astype(np.int32).reshape(D, 1), self._sharding)
            max_fc = int(fcounts.max())
            chunks_done = 0
            tl_sample = tl_every > 0 and (depth + 1) % tl_every == 0
            stage_s = {
                "expand": 0.0, "exchange": 0.0, "emit": 0.0,
                "seen_merge": 0.0, "checkpoint": 0.0,
            }
            with tel.wave_annotation(depth + 1):
                for cursor in range(0, max_fc, C):
                    occ_dev = self._occ_dev()
                    if tl_sample:
                        pre_fn, ex_fn, post_fn = self._get_timeline_fns(
                            len(self._lsm.runs))
                        t1 = time.perf_counter()
                        (send_pay, send_fps, state["memo"], cov_gen,
                         pre_stats) = pre_fn(
                            state["frontier"], fc_dev, state["memo"],
                            np.int32(cursor), bl_dev,
                        )
                        # lint: sync-ok(stage attribution on a sampled wave)
                        jax.block_until_ready(
                            (send_pay, send_fps, state["memo"], cov_gen,
                             pre_stats))
                        t2 = time.perf_counter()
                        stage_s["expand"] += t2 - t1
                        recv_pay, recv_fps = ex_fn(send_pay, send_fps)
                        # lint: sync-ok(stage attribution on a sampled wave)
                        jax.block_until_ready((recv_pay, recv_fps))
                        t3 = time.perf_counter()
                        stage_s["exchange"] += t3 - t2
                        (state["next_buf"], state["jps"], state["jpl"],
                         state["jcand"], state["jfp"], state["viol"],
                         state["stats"], state["cov"], new_run,
                         ) = post_fn(
                            recv_pay, recv_fps, state["next_buf"],
                            state["jps"], state["jpl"], state["jcand"],
                            state["jfp"], state["viol"], state["stats"],
                            state["cov"], cov_gen, pre_stats, occ_dev,
                            *self._lsm.runs,
                        )
                        # lint: sync-ok(stage attribution on a sampled wave)
                        jax.block_until_ready(new_run)
                        t4 = time.perf_counter()
                        stage_s["emit"] += t4 - t3
                        self._lsm.insert(new_run)
                        # lint: sync-ok(stage attribution on a sampled wave)
                        jax.block_until_ready(self._lsm.runs)
                        stage_s["seen_merge"] += time.perf_counter() - t4
                    else:
                        chunk_fn = self._get_chunk_fn(len(self._lsm.runs))
                        (state["next_buf"], state["jps"], state["jpl"],
                         state["jcand"], state["jfp"], state["viol"],
                         state["stats"], state["memo"], state["cov"],
                         new_run,
                         ) = chunk_fn(
                            state["frontier"], fc_dev, state["next_buf"],
                            state["jps"], state["jpl"], state["jcand"],
                            state["jfp"], state["viol"], state["stats"],
                            state["memo"], state["cov"], np.int32(cursor),
                            occ_dev, bl_dev, *self._lsm.runs,
                        )
                        self._lsm.insert(new_run)
                    chunks_done += 1
                    if chaos is not None:
                        lost = chaos.shard_loss(depth + 1, D)
                        if lost is not None:
                            # deterministic stand-in for a device dying
                            # mid-wave: spill a wave-start checkpoint
                            # (jfp subtraction — mid-wave the LSM holds
                            # only the chunks already inserted, and the
                            # jfp lane recorded exactly those), classify,
                            # and let the supervisor reshard onto the
                            # survivors
                            # lint: sync-ok(wave-start spill on shard loss)
                            stats_mid = np.asarray(
                                jax.device_get(state["stats"]))
                            saved = self._abort_wave_start(
                                checkpoint_path, state, stats_mid,
                                fcounts, scounts, jcounts, n0, base_lgid,
                                distinct, total, terminal + term_base,
                                depth, gen_prev + gen_base,
                                routed_prev + routed_base, depth_counts,
                                cov_hd,
                            )
                            tel.event(
                                "shard_lost", wave=depth + 1, depth=depth,
                                shard=int(lost), device_count=D,
                                checkpoint_saved=bool(saved))
                            raise ShardLost(
                                f"shard {lost} lost its device mid-wave "
                                f"{depth + 1} (chaos)",
                                shard=int(lost), checkpoint_saved=saved,
                            )
                # cov rides the same once-per-wave fetch — no extra
                # device_get calls with coverage on
                # lint: sync-ok(once-per-wave snapshot)
                stats_h, viol_h, cov_w = jax.device_get(
                    (state["stats"], state["viol"], state["cov"]))
            stats_h = np.asarray(stats_h)  # [D,7]
            viol_h = np.asarray(viol_h)  # [D,K]
            new_d = stats_h[:, 0]
            ovf_bits = int(np.bitwise_or.reduce(stats_h[:, 4]))
            if chaos is not None:
                ovf_bits = chaos.ovf_bits(ovf_bits, depth + 1, 8)
            if ovf_bits:
                # the chunk loop already inserted this wave's fps into
                # the LSM, but the jfp lane journalled exactly what was
                # inserted — _abort_wave_start subtracts the aborted
                # wave back out and spills a wave-start checkpoint, so
                # a grown resume loses zero work (parity with DeviceBFS)
                stats_abort = stats_h.copy()
                stats_abort[:, 4] = ovf_bits  # incl. chaos-injected bits
                saved = self._abort_wave_start(
                    checkpoint_path, state, stats_abort, fcounts, scounts,
                    jcounts, n0, base_lgid, distinct, total,
                    terminal + term_base, depth, gen_prev + gen_base,
                    routed_prev + routed_base, depth_counts, cov_hd,
                )
                raise CapacityOverflow(
                    f"sharded BFS capacity overflow (bits={ovf_bits:05b}: "
                    "1=msg-slots 2=valid_per_state/valid_per_group "
                    "4=route_cap 8=frontier_cap 16=journal_cap)"
                    + (f"; wave-start checkpoint saved to {checkpoint_path}"
                       if saved else ""),
                    what=tuple(
                        name for bit, name in self.OVF_NAMES
                        if ovf_bits & bit),
                    bits=ovf_bits,
                    checkpoint_saved=saved,
                )
            # per-shard stall watchdog: a wave pathologically slower than
            # the rolling median flags a sick device (thermal throttle,
            # ICI link flap) — classify instead of hanging the fleet. The
            # ovf check above already passed, so the jfp lane holds the
            # whole wave and the wave-start spill is exact.
            wave_s_now = time.perf_counter() - tw
            if stall_abort_factor is not None and len(wave_times) >= 3:
                med = float(np.median(wave_times[-16:]))
                if med > 0 and wave_s_now > stall_abort_factor * med:
                    suspect = int(np.argmax(new_d))  # most-loaded shard
                    saved = self._abort_wave_start(
                        checkpoint_path, state, stats_h, fcounts, scounts,
                        jcounts, n0, base_lgid, distinct, total,
                        terminal + term_base, depth, gen_prev + gen_base,
                        routed_prev + routed_base, depth_counts, cov_hd,
                    )
                    tel.event(
                        "shard_stall", wave=depth + 1, depth=depth,
                        shard=suspect, wave_s=round(wave_s_now, 3),
                        median_wave_s=round(med, 3),
                        factor=round(wave_s_now / med, 3))
                    raise ShardStall(
                        f"wave {depth + 1} took {wave_s_now:.3f}s against "
                        f"a rolling median of {med:.3f}s "
                        f"(factor {wave_s_now / med:.1f} > "
                        f"{stall_abort_factor}); suspect shard {suspect}",
                        shard=suspect, wave_s=wave_s_now, median_s=med,
                        checkpoint_saved=saved,
                    )
            wave_times.append(wave_s_now)
            # phase split: everything up to the stats fetch is device-
            # blocked time; checkpoint I/O is bracketed below; the
            # residual (growth, LSM bookkeeping) lands in host_s
            device_s = wave_s_now
            ckpt_s = 0.0
            # commit only after the ovf check: an aborted wave keeps the
            # wave-start counters (consistent with what a checkpoint saved)
            cov_hd = np.asarray(cov_w, dtype=np.int64)
            global_new = int(new_d.sum())
            n_gen_cum = int(stats_h[:, 2].sum())
            wave_gen = n_gen_cum - gen_prev
            total += wave_gen
            gen_prev = n_gen_cum
            terminal = int(stats_h[:, 3].sum())
            wave_routed = int(stats_h[:, 5].sum()) - routed_prev
            routed_prev = int(stats_h[:, 5].sum())
            wave_routed_d = stats_h[:, 5] - routed_prev_d
            routed_prev_d = stats_h[:, 5].copy()
            memo_hits = int(stats_h[:, 6].sum())
            wave_memo = memo_hits - memo_prev
            memo_prev = memo_hits
            per_shard_memo = stats_h[:, 6].copy()
            if global_new == 0:
                exit_cause = "exhausted"
                break
            depth += 1
            distinct += global_new
            depth_counts.append(global_new)
            base_lgid = n0 + stats_h[:, 1] - new_d
            scounts += new_d
            jcounts = stats_h[:, 1].copy()
            if self.invariants and (viol_h != I32_MAX).any():
                # first violated invariant (cfg order), lowest jidx,
                # lowest shard as the tie-break
                for k, name in enumerate(self.invariants):
                    col = viol_h[:, k]
                    if (col != I32_MAX).any():
                        d = int(np.argmin(col))
                        violation = name
                        viol_site = (d, int(n0[d] + col[d]))
                        break
            # reset the wave-new counter (stats was donated; rebuild)
            stats_h2 = stats_h.copy()
            stats_h2[:, 0] = 0
            state["stats"] = jax.device_put(stats_h2, self._sharding)
            state["frontier"], state["next_buf"] = (
                state["next_buf"], state["frontier"])
            prev_fcounts = fcounts
            fcounts = new_d.copy()
            if violation is None:
                state = self._maybe_grow(state, fcounts, jcounts)
                # per-chip floor is smaller than DeviceBFS's (1<<21):
                # each chip holds ~1/D of the space
                if self._lsm.lanes() > max(4 * int(scounts.max()), 1 << 20):
                    with tel.annotate("consolidate"):
                        self._lsm.consolidate(int(scounts.max()))
                if (
                    checkpoint_path is not None
                    and time.perf_counter() - last_ckpt > checkpoint_every_s
                ):
                    t_ck = time.perf_counter()
                    with tel.annotate("checkpoint"):
                        self._save_checkpoint(
                            checkpoint_path, state, fcounts, scounts,
                            jcounts, n0, base_lgid, distinct, total,
                            terminal + term_base, depth,
                            gen_prev + gen_base,
                            routed_prev + routed_base, depth_counts,
                            cov_hd,
                        )
                    last_ckpt = time.perf_counter()
                    ckpt_s = last_ckpt - t_ck
                    stage_s["checkpoint"] += ckpt_s
            wave_s_val = time.perf_counter() - tw
            if tl_every:
                (tl_wave_s if tl_sample else fused_wave_s).append(wave_s_val)
            if tel.active or metrics is not None or verbose:
                el = time.perf_counter() - t0
                hbm_frac = None
                if memwatch is not None:
                    # PER-CHIP analytic live bytes (the budget is one
                    # core's HBM): double-buffered frontier, 4-lane
                    # journal, this chip's LSM lanes, the chunk scratch
                    # (payload + send/recv blocks), the canon memo
                    frac = memwatch.update(depth, depth, {
                        "frontier": 2 * (self.FCAP + self.EPAD) * 4 * W,
                        "journal": (self.JCAP + self.EPAD) * (4 * 3 + 8),
                        "seen": int(self._lsm.lanes()) * 8,
                        "chunk": (self.VC + 2 * self.D * self.RC)
                        * (4 * (W + 3) + 8),
                        "memo": self.MCAP * 16 if self._use_memo else 0,
                    })
                    hbm_frac = round(frac, 6)
                tl_dev = (
                    stage_s["expand"] + stage_s["exchange"]
                    + stage_s["emit"]
                )
                wm = {
                    "depth": depth,
                    "frontier": int(prev_fcounts.sum()),
                    "new": global_new,
                    "distinct": distinct,
                    "generated": wave_gen,
                    "generated_total": total,
                    "terminal": terminal + term_base,
                    "dedup_hit_rate": round(1.0 - global_new / max(1, wave_gen), 4),
                    "canon_memo_hits": wave_memo,
                    "canon_memo_hit_rate": round(
                        wave_memo / max(1, wave_gen), 4
                    ),
                    "overflow_bits": ovf_bits,
                    "wave_s": round(wave_s_val, 3),
                    "elapsed_s": round(el, 3),
                    "distinct_per_s": round(distinct / el, 1),
                    "device_s": round(device_s, 4),
                    "host_s": round(
                        max(0.0, wave_s_val - device_s - ckpt_s), 4),
                    "ckpt_s": round(ckpt_s, 4),
                    "tel_s": round(tel_s_last, 4),
                    # exchange share of the sampled wave's staged device
                    # seconds; null on fused (unsampled) waves — the
                    # fused program cannot separate the all-to-all
                    "exchange_share": round(
                        stage_s["exchange"] / tl_dev, 4)
                    if tl_sample and tl_dev > 0 else None,
                    "hbm_frac": hbm_frac,
                    "a2a_lanes": wave_routed,
                    # payload widened to W+3 by the routed rank column
                    "a2a_bytes": wave_routed * (4 * (W + 3) + 8),
                    "shard_new": [int(x) for x in new_d],
                    "shard_new_min": int(new_d.min()),
                    "shard_new_max": int(new_d.max()),
                    "lsm_runs": sum(self._lsm.occ),
                    "lsm_lanes": int(self._lsm.lanes()),
                    # emit gauges (round 6): fleet rows appended, bytes
                    # the append path WROTE (one [D*RC, W] block + three
                    # journal lanes per chip per chunk), and the worst
                    # chip's frontier occupancy — frontier_fill nearing
                    # 1.0 flags an imminent growth/overflow wave for the
                    # stall watchdog
                    "emit_rows": global_new,
                    "emit_bytes": chunks_done * D * (D * self.RC)
                    * (4 * W + 12),
                    "frontier_fill": round(int(new_d.max()) / self.FCAP, 4),
                    # sparse-expand gauges (checker/device_bfs.py): both
                    # derive from counters this wave already fetched
                    "enabled_density": round(
                        wave_gen / max(1, int(prev_fcounts.sum()) * self.A),
                        4,
                    ),
                    "expand_budget_ovf": (ovf_bits >> 1) & 1,
                }
                t_tel = time.perf_counter()
                tel.wave(wm)
                if tel.active:
                    tel.coverage(self._coverage_fields(
                        depth, cov_hd, scounts, depth_counts))
                    if tl_sample:
                        tel.event(
                            "timeline", wave=depth, depth=depth,
                            every=tl_every,
                            stages={
                                k: round(v, 5)
                                for k, v in stage_s.items() if v > 0
                            },
                            wave_s=round(wave_s_val, 4),
                        )
                        # per-shard critical-path rows: lockstep SPMD
                        # shares the wall clock, so shard_s is the
                        # analytic attribution compute_s*work_share*D
                        # (skew = max - median over shards)
                        comp_s = stage_s["expand"] + stage_s["emit"]
                        for d in range(D):
                            ws = int(new_d[d]) / max(1, global_new)
                            tel.event(
                                "shard_wave", wave=depth, depth=depth,
                                shard=d, device_count=D,
                                new=int(new_d[d]),
                                routed_lanes=int(wave_routed_d[d]),
                                routed_bytes=int(wave_routed_d[d])
                                * (4 * (W + 3) + 8),
                                work_share=round(ws, 4),
                                shard_s=round(comp_s * ws * D, 5),
                                exchange_s=round(stage_s["exchange"], 5),
                                compute_s=round(comp_s, 5),
                            )
                if metrics is not None:
                    metrics.append(wm)
                if verbose:
                    print(
                        f"depth {depth}: +{global_new} distinct={distinct} "
                        f"a2a={wave_routed} lanes "
                        f"balance={new_d.min()}/{new_d.max()} "
                        f"({distinct/el:.0f} distinct/s)",
                        file=sys.stderr)
                tel_s_last = time.perf_counter() - t_tel

        if (checkpoint_path is not None and violation is None
                and not exhausted):
            self._save_checkpoint(
                checkpoint_path, state, fcounts, scounts, jcounts, n0,
                base_lgid, distinct, total, terminal + term_base, depth,
                gen_prev + gen_base, routed_prev + routed_base, depth_counts,
                cov_hd,
            )

        # fetch journals for trace reconstruction
        jps_h = np.asarray(jax.device_get(state["jps"]))
        jpl_h = np.asarray(jax.device_get(state["jpl"]))
        jcand_h = np.asarray(jax.device_get(state["jcand"]))
        self._journals = (jps_h, jpl_h, jcand_h, jcounts.copy(), n0.copy())

        dt = time.perf_counter() - t0
        if violation is not None:
            exit_cause = "violation"
        elif exit_cause is None:
            exit_cause = "exhausted"
        # fleet aggregates (satellite of the telemetry PR): memo hit
        # totals + per-shard skew, from the SAME host stats the loop
        # already fetched — also returned on ShardedResult.stats
        fleet_rate = round(memo_prev / max(1, gen_prev), 4)
        fleet_cov = cov_hd.sum(axis=0)
        fleet_stats = {
            "canon_memo_hits": memo_prev,
            "canon_memo_hit_rate": fleet_rate,
            "shard_memo_hits": [int(x) for x in per_shard_memo],
            "shard_distinct": [int(x) for x in scounts],
            "shard_skew": round(
                int(scounts.max()) / max(1, int(scounts.min())), 3),
            "coverage": [[int(x) for x in row] for row in fleet_cov],
        }
        # final canon-memo fill ratio: one device reduction, done whether
        # or not telemetry is attached so the zero-sync guarantee (equal
        # device_get call counts) holds either way
        if self._use_memo:
            filled = int(np.asarray(jax.device_get(
                jnp.sum(ne_u64(state["memo"][:, :, 0], U64_MAX))
            )))
            memo_fill = round(filled / max(1, self.D * self.MCAP), 4)
        else:
            memo_fill = None
        if tel.active:
            cf = self._coverage_fields(depth, cov_hd, scounts, depth_counts)
            cf["canon_memo_fill"] = memo_fill
            tel.coverage(cf, final=True)
        tl_extras = {}
        if tl_every:
            mt = sum(tl_wave_s) / len(tl_wave_s) if tl_wave_s else None
            mf = (
                sum(fused_wave_s) / len(fused_wave_s)
                if fused_wave_s else None
            )
            tl_extras = {
                "timeline_every": tl_every,
                "timeline_waves": len(tl_wave_s),
                # per-wave extra cost of the staged dispatches,
                # amortized over the stride
                "timeline_overhead": round((mt - mf) / (mf * tl_every), 4)
                if mt is not None and mf else None,
            }
        tel.close_run({
            "engine": "sharded",
            "ident": self._ckpt_ident(),
            "exit_cause": exit_cause,
            "violation": violation,
            "distinct": distinct,
            "total": total,
            "depth": depth,
            "terminal": terminal + term_base,
            "seconds": round(dt, 3),
            "distinct_per_s": round(distinct / dt, 1) if dt > 0 else 0.0,
            "exhausted": exhausted and violation is None,
            "peak_frontier_cap": self.FCAP,
            "peak_journal_cap": self.JCAP,
            "seen_lanes": int(self._lsm.lanes()),
            "canon_memo_hit_rate": fleet_rate,
            # sharded extras (schema allows extra keys)
            "shard_memo_hits": fleet_stats["shard_memo_hits"],
            "shard_skew": fleet_stats["shard_skew"],
            **tl_extras,
            **(memwatch.summary_fields() if memwatch is not None else {}),
        })
        trace = init_trace
        if violation is not None and viol_site is not None:
            trace = self.reconstruct_trace(viol_site)
        return ShardedResult(
            distinct=distinct,
            total=total,
            depth=depth,
            depth_counts=depth_counts,
            violation_invariant=violation,
            seconds=dt,
            states_per_sec=distinct / dt if dt > 0 else 0.0,
            terminal=terminal + term_base,
            exhausted=exhausted and violation is None,
            trace=trace,
            metrics=metrics,
            stats=fleet_stats,
            coverage=(fleet_stats["coverage"] if self.n_actions else None),
            exit_cause=exit_cause,
        )

    def run_fleet(
        self,
        job_names: list[str] | None = None,
        telemetry=None,
        checkpoint_dir: str | None = None,
        checkpoint_every_s: float = 300.0,
        checkpoint_keep: int = rckpt.DEFAULT_KEEP,
        resume: bool = False,
        skip: tuple[str, ...] = (),
        supervise: int | None = None,
        chaos_by_job: dict | None = None,
        recovery_stats: dict | None = None,
        **run_kw,
    ) -> list:
        """Fleet queue arm over all shards: same contract as
        DeviceBFS.run_fleet — sequential jobs through one engine
        instance (``fleet_select`` swaps only the stamped init states,
        so the sharded programs compile once per group), job-tagged
        telemetry, and one checkpoint lineage per job under
        ``checkpoint_dir`` (named by ``resilience.lineage_name``, which
        disambiguates sanitizer collisions with the job index).

        ``supervise``: when set, each job runs under the resilience
        supervisor with that per-job recovery budget; the engine factory
        returns THIS instance for empty overrides, so recoveries that
        need no growth/reshard reuse the compiled programs (zero
        recompiles). A job whose budget is spent (or whose failure has
        no recovery policy) contributes its UnrecoverableError /
        CheckpointMismatch to the results list instead of killing the
        rest of the fleet. ``chaos_by_job`` maps job name -> a
        ChaosInjector for that job only. ``recovery_stats`` (a dict) is
        filled in place with job name -> recovery count."""
        import os

        from ..obs.collector import JobTaggedTelemetry

        model = self.model
        J = model.fleet_jobs
        if J == 0:
            raise ValueError(
                "run_fleet needs a fleet-bound model (fleet_bind)"
            )
        names = list(job_names) if job_names else [f"job{j}" for j in range(J)]
        if len(names) != J:
            raise ValueError(f"{len(names)} job names for {J} jobs")
        results = []
        try:
            for j, name in enumerate(names):
                if name in skip:
                    results.append(None)
                    continue
                model.fleet_select(j)
                kw = dict(run_kw)
                if telemetry is not None:
                    kw["telemetry"] = JobTaggedTelemetry(telemetry, name)
                if chaos_by_job and name in chaos_by_job:
                    kw["chaos"] = chaos_by_job[name]
                if checkpoint_dir is not None:
                    ck = os.path.join(
                        checkpoint_dir, rckpt.lineage_name(name, j))
                    kw.setdefault("checkpoint_path", ck)
                    kw.setdefault("checkpoint_every_s", checkpoint_every_s)
                    kw.setdefault("checkpoint_keep", checkpoint_keep)
                    if resume and os.path.exists(ck):
                        kw.setdefault("resume", ck)
                if supervise is None:
                    results.append(self.run(**kw))
                    continue
                results.append(self._run_supervised(
                    kw, int(supervise), j, name, recovery_stats))
        finally:
            model.fleet_select(None)
        return results

    def _run_supervised(self, kw, budget, job_index, name, recovery_stats):
        """One fleet job under the resilience supervisor. Returns the
        run result, or the terminal exception object when the job's
        recovery budget is spent (the fleet driver maps it to an
        ``unrecoverable`` JobResult)."""
        from ..resilience import (
            CheckpointMismatch,
            UnrecoverableError,
            supervise as _supervise,
        )

        def factory(overrides):
            # empty overrides -> the cached engine: recoveries that need
            # neither growth nor a shrunk mesh stay recompile-free
            return self if not overrides else self._rebuild(overrides)

        stats: dict = {}
        try:
            res = _supervise(
                factory, kw, max_retries=budget, backoff_base=0.0,
                seed=job_index, telemetry=kw.get("telemetry"),
                stats_out=stats,
            )
        except (UnrecoverableError, CheckpointMismatch) as exc:
            res = exc
        if recovery_stats is not None:
            recovery_stats[name] = int(stats.get("recoveries", 0))
        return res

    def _coverage_fields(self, depth, cov_hd, scounts, depth_counts) -> dict:
        """Coverage-event payload (obs.events.COVERAGE_KEYS), fleet-summed
        from the per-shard [D, n_actions, 3] counters. Dedup gauges come
        from the shared LSM geometry (identical on every chip)."""
        fleet = cov_hd.sum(axis=0)
        occ = list(self._lsm.occ)
        return {
            "depth": depth,
            "actions": [[int(x) for x in row] for row in fleet],
            "actions_total": self.n_actions,
            "actions_fired": int(np.count_nonzero(fleet[:, 1]))
            if self.n_actions else 0,
            "seen_lanes": [
                int(r.shape[-1]) for r, o in zip(self._lsm.runs, occ) if o
            ],
            "seen_real": int(scounts.sum()),
            "probe_runs": int(sum(occ)),
            "frontier_hist": [int(x) for x in depth_counts],
            "canon_memo_fill": None,  # final snapshot only
        }

    def _telemetry_manifest(self) -> dict:
        """Run-provenance fields of the telemetry manifest event."""
        dev = self.mesh.devices.flat[0]
        ident = self._ckpt_ident()
        return {
            "engine": "sharded",
            "ident": ident,
            "hashv": hashv_of(ident),
            "model": self.model.name,
            "platform": dev.platform,
            "device": str(getattr(dev, "device_kind", dev.platform)),
            "device_count": self.D,
            "chunk": self.chunk,
            "frontier_cap": self.FCAP,
            "journal_cap": self.JCAP,
            "max_seen_cap": self.MAX_SCAP,
            "valid_cap": self.VC,
            "canon_memo_cap": self.MCAP if self._use_memo else 0,
            "symmetry": bool(self.canon.symmetry),
            "invariants": list(self.invariants),
            "action_names": list(getattr(self.model, "ACTION_NAMES", ())),
            "when": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }

    def _check_init(self, init_d: np.ndarray):
        """(invariant name, index of first bad init state) or None."""
        for name in self.invariants:
            ok = np.asarray(jax.device_get(self.model.invariants[name](init_d)))
            bad = np.nonzero(~ok)[0]
            if len(bad):
                return name, int(bad[0])
        return None

    # ---------------- trace reconstruction ----------------

    def reconstruct_trace(self, site: tuple[int, int]) -> list[tuple[str, dict]]:
        """Walk (shard, local gid) parent pointers to an Init state, then
        replay the recorded candidate actions forward (same semantics as
        DeviceBFS.reconstruct_trace; journal entries just live per shard)."""
        model = self.model
        jps_h, jpl_h, jcand_h, jcounts, n0 = self._journals
        d, lgid = site
        chain: list[int] = []
        while lgid >= n0[d]:
            j = int(lgid - n0[d])
            assert j < jcounts[d], "journal index out of range"
            chain.append(int(jcand_h[d, j]))
            d, lgid = int(jps_h[d, j]), int(jpl_h[d, j])
        chain.reverse()
        state = self._init_by_shard[d][int(lgid)]
        out = [("Initial predicate", model.decode(state))]
        expand1 = jax.jit(model._expand1)
        for cand in chain:
            succs, valid, rank, _ovf = jax.device_get(expand1(state))
            assert valid[cand], "journalled candidate not enabled on replay"
            state = np.asarray(succs[cand])
            out.append(
                (model.action_label(int(rank[cand]), cand), model.decode(state)))
        return out
