"""Guard-purity pass: every model's DCE-derived guard pass writes no
W-wide successor rows and reads state only through declared layout
fields.

Generalizes the single ``tests/test_expand_sparse.py`` jaxpr pin to a
registry-wide audit. The guard-first sparse expansion exists so the
per-chunk guard grid costs O(A) scalars per state instead of
materializing the [A, W] successor block; a refactor of ``_expand1``
that lets a successor write survive DCE silently reverts the split's
entire win. Three checks per family, on ``model.guards1.jaxpr``:

  * no equation output is a ``[*, W]`` block (ndim >= 2 with a W-sized
    trailing axis) — single [W] vectors are fine, the input state is
    one;
  * DCE actually removed work — the guard jaxpr is strictly smaller
    than the full ``_expand1`` jaxpr;
  * every static slice of the state vector falls inside ONE declared
    layout field span (guards read whole lanes of declared fields;
    a slice straddling fields means the guard is reading a lane the
    layout registry does not declare at that offset). Reads through
    gathers or of the whole state vector are conservatively allowed.
"""

from __future__ import annotations

import time

from .findings import Finding, PassResult, site_of

PASS_ID = "guard-purity"

# the hook the mutation self-test overrides (a fresh, never-cached
# model with a poisoned guard derivation) — production resolves through
# the registry's shared cached_model instances
def _default_model(fam: str):
    from . import registry

    return registry.tiny_model(fam)


MODEL_FN = _default_model


def _state_slices(jaxpr, state_var):
    """Static (start, limit) spans sliced out of the state vector, plus
    a flag for non-slice reads (gather/dynamic_slice/whole-vector use)
    that the span check cannot see through."""
    spans = []
    opaque = False
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if v is not state_var:
                continue
            if str(eqn.primitive) == "slice":
                spans.append((
                    int(eqn.params["start_indices"][0]),
                    int(eqn.params["limit_indices"][0]),
                ))
            else:
                opaque = True
    return spans, opaque


def check_model(fam: str, model, findings: list) -> int:
    import jax
    import jax.numpy as jnp

    checked = 0
    W = model.layout.W
    path, line = site_of(type(model)._build_guards1)
    jx = model.guards1.jaxpr

    checked += 1
    wide = [
        (str(e.primitive), tuple(v.aval.shape))
        for e in jx.eqns
        for v in e.outvars
        if getattr(v.aval, "ndim", 0) >= 2 and v.aval.shape[-1] == W
    ]
    if wide:
        findings.append(Finding(
            PASS_ID, "error", path, line,
            f"{fam}: guard jaxpr materializes W-wide successor rows — "
            f"the sparse split's whole point is to never build these "
            f"in the guard pass",
            {"family": fam, "w": W,
             "eqns": [f"{p} -> {s}" for p, s in wide]},
        ))

    checked += 1
    full = jax.make_jaxpr(model._expand1)(
        jax.ShapeDtypeStruct((W,), jnp.int32)).jaxpr
    if not len(jx.eqns) < len(full.eqns):
        findings.append(Finding(
            PASS_ID, "error", path, line,
            f"{fam}: DCE removed nothing from the guard jaxpr "
            f"({len(jx.eqns)} eqns vs full {len(full.eqns)}) — the "
            f"guard pass is doing the apply pass's work",
            {"family": fam, "guard_eqns": len(jx.eqns),
             "full_eqns": len(full.eqns)},
        ))

    # read-lane discipline: static state slices sit inside one field
    checked += 1
    state_var = jx.invars[-1] if jx.invars else None
    spans_decl = sorted(
        (f.offset, f.offset + f.size) for f in model.layout.fields.values()
    )
    if state_var is not None and getattr(
            state_var.aval, "shape", None) == (W,):
        spans, _opaque = _state_slices(jx, state_var)
        for start, limit in spans:
            inside = any(
                lo <= start and limit <= hi for lo, hi in spans_decl)
            if not inside:
                findings.append(Finding(
                    PASS_ID, "error", path, line,
                    f"{fam}: guard reads state lanes [{start}:{limit}) "
                    f"which straddle the declared layout fields — the "
                    f"layout registry declares no field at that span",
                    {"family": fam, "span": [start, limit]},
                ))
    return checked


def run(families=None) -> PassResult:
    from . import registry

    t0 = time.time()
    families = tuple(families) if families else registry.FAMILIES
    findings: list[Finding] = []
    checked = 0
    skipped = []
    for fam in families:
        model = MODEL_FN(fam)
        if not hasattr(model, "_build_guards1"):
            skipped.append(fam)
            continue
        checked += check_model(fam, model, findings)
    notes = [f"guard jaxprs of {len(families) - len(skipped)} families"]
    if skipped:
        notes.append(f"skipped (no sparse guard pass): {skipped}")
    return PassResult(PASS_ID, findings, checked, time.time() - t0, notes)
