"""The findings model every lint pass reports through.

A finding is ``file:line``-anchored (repo-relative, so output is stable
across checkouts), carries the pass id and a severity, and serializes to
JSON for machine consumers (``raft_tpu lint --json``, the bench.py
provenance block). Severity semantics follow the CLI contract:

  error    a broken contract — ``lint`` exits 3 even without --strict
  warning  a drift/coverage gap — exits 3 only under --strict
  info     advisory (reported, never gates)
"""

from __future__ import annotations

import dataclasses
import inspect
import os

SEVERITIES = ("error", "warning", "info")

# raft_tpu/analysis/findings.py -> the repo checkout root
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def rel(path: str) -> str:
    """Repo-relative form of ``path`` (pass through if already outside
    the checkout — fixture sources in tests report their given name)."""
    ap = os.path.abspath(path)
    if ap.startswith(REPO_ROOT + os.sep):
        return os.path.relpath(ap, REPO_ROOT)
    return path


def site_of(obj) -> tuple[str, int]:
    """(repo-relative file, first line) of a function/method/class —
    the anchor for findings about a program built from that code."""
    obj = inspect.unwrap(obj)
    try:
        path = inspect.getsourcefile(obj) or "<unknown>"
        _, line = inspect.getsourcelines(obj)
    except (OSError, TypeError):
        return "<unknown>", 0
    return rel(path), line


@dataclasses.dataclass
class Finding:
    pass_id: str
    severity: str
    path: str
    line: int
    message: str
    detail: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        assert self.severity in SEVERITIES, self.severity
        self.path = rel(self.path)

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {
            "pass": self.pass_id,
            "severity": self.severity,
            "file": self.path,
            "line": self.line,
            "message": self.message,
            "detail": self.detail,
        }

    def render(self) -> str:
        out = (
            f"{self.severity.upper():7s} [{self.pass_id}] "
            f"{self.location}: {self.message}"
        )
        if self.detail:
            pairs = " ".join(f"{k}={v}" for k, v in sorted(self.detail.items()))
            out += f"  ({pairs})"
        return out


@dataclasses.dataclass
class PassResult:
    """One pass run: its findings plus how much it actually audited
    (``checked`` = programs lowered / modules scanned / families proved —
    a pass that silently audits nothing must not read as clean)."""

    pass_id: str
    findings: list[Finding]
    checked: int
    seconds: float = 0.0
    notes: list[str] = dataclasses.field(default_factory=list)

    @property
    def errors(self) -> int:
        return sum(1 for f in self.findings if f.severity == "error")

    @property
    def warnings(self) -> int:
        return sum(1 for f in self.findings if f.severity == "warning")

    def to_dict(self) -> dict:
        return {
            "pass": self.pass_id,
            "checked": self.checked,
            "seconds": round(self.seconds, 3),
            "findings": [f.to_dict() for f in self.findings],
            "notes": self.notes,
        }
