"""Hidden-sync detector: no device sync inside a chunk/wave loop.

The engines' zero-extra-sync guarantee — one ``device_get`` per wave,
everything else async dispatch — is what keeps the host out of the
device's way (and keeps telemetry from perturbing what it measures: the
observatory PR's first design cost a sync per chunk and skewed every
stage it attributed). This pass walks the AST of the HOT loop bodies
(``DeviceBFS.run`` / ``_run_timeline_wave`` / ``run_fleet``,
``ShardedBFS.run`` / ``run_fleet``) and flags calls that force a
host-device round trip inside a ``for``/``while`` body:

  * ``jax.device_get(...)`` / ``jax.block_until_ready(...)``
  * ``.item()`` on anything
  * ``np.asarray(<call>)`` — wrapping a device-returning call forces
    materialization (plain ``np.asarray(host_array)`` is not flagged)

Blessed sites carry a ``lint: sync-ok(<why>)`` comment on the
statement or the line above it: the once-per-wave snapshot, the
sampled-wave stage attribution barriers (--timeline), and the
wave-start spill on shard loss. The analysis is intra-function —
helpers called from the loop (checkpoint writers, abort paths) run
once per EVENT, not per chunk, and are out of scope by design.
"""

from __future__ import annotations

import ast
import os
import time

from .findings import Finding, PassResult, rel

PASS_ID = "hidden-sync"

BLESS_MARK = "lint: sync-ok"

# (repo-relative file) -> hot function names whose loop bodies must be
# sync-free; host-side modules (checker/bfs.py, simulate) are excluded
# by policy — they ARE the host loop.
HOT_SCOPES = {
    os.path.join("raft_tpu", "checker", "device_bfs.py"):
        ("run", "_run_timeline_wave", "run_fleet"),
    os.path.join("raft_tpu", "parallel", "sharded.py"):
        ("run", "run_fleet"),
}

# the hook the mutation self-test overrides: {rel_path: source_text}
SOURCE_OVERRIDES: dict | None = None


def _sync_call_kind(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr == "item":
            return ".item()"
        if (isinstance(fn.value, ast.Name) and fn.value.id == "jax"
                and fn.attr in ("device_get", "block_until_ready")):
            return f"jax.{fn.attr}"
        if (isinstance(fn.value, ast.Name) and fn.value.id == "np"
                and fn.attr == "asarray" and call.args
                and isinstance(call.args[0], ast.Call)):
            return "np.asarray(<call>)"
    return None


def _blessed(lines: list[str], stmt: ast.stmt) -> bool:
    lo = max(0, stmt.lineno - 2)  # line above the statement
    hi = min(len(lines), getattr(stmt, "end_lineno", stmt.lineno))
    return any(BLESS_MARK in lines[i] for i in range(lo, hi))


def _loop_statements(fn: ast.FunctionDef):
    """Yield every statement nested inside a For/While body of ``fn``
    (inner functions are their own scopes and are skipped)."""
    def stmts_under(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.stmt):
                yield child
            yield from stmts_under(child)

    seen = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue
        if isinstance(node, (ast.For, ast.While)):
            for stmt in stmts_under(node):
                key = (stmt.lineno, stmt.col_offset)
                if key not in seen:
                    seen.add(key)
                    yield stmt


def scan_source(src: str, path: str, hot_names, findings: list) -> int:
    """Scan one module's source; returns the number of hot functions
    audited. ``path`` is used only for anchoring findings."""
    lines = src.splitlines()
    tree = ast.parse(src)
    audited = 0
    flagged = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name not in hot_names:
            continue
        audited += 1
        for stmt in _loop_statements(node):
            for call in ast.walk(stmt):
                if not isinstance(call, ast.Call):
                    continue
                kind = _sync_call_kind(call)
                if kind is None:
                    continue
                key = (call.lineno, call.col_offset)
                if key in flagged:
                    continue
                flagged.add(key)
                if _blessed(lines, stmt):
                    continue
                findings.append(Finding(
                    PASS_ID, "error", path, call.lineno,
                    f"{kind} inside the {node.name}() chunk/wave loop "
                    f"— a host-device sync per iteration; hoist it to "
                    f"the once-per-wave snapshot or bless it with "
                    f"'# {BLESS_MARK}(<why>)'",
                    {"function": node.name, "call": kind},
                ))
    return audited


def run() -> PassResult:
    from .findings import REPO_ROOT

    t0 = time.time()
    findings: list[Finding] = []
    checked = 0
    for relpath, hot_names in sorted(HOT_SCOPES.items()):
        if SOURCE_OVERRIDES and relpath in SOURCE_OVERRIDES:
            src = SOURCE_OVERRIDES[relpath]
        else:
            with open(os.path.join(REPO_ROOT, relpath)) as fh:
                src = fh.read()
        checked += scan_source(src, rel(relpath), hot_names, findings)
    notes = [f"hot loops in {len(HOT_SCOPES)} engine modules"]
    return PassResult(PASS_ID, findings, checked, time.time() - t0, notes)
