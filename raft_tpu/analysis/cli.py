"""``raft_tpu lint`` — the kernel contract auditor's CLI.

Usage:
  python -m raft_tpu lint [--strict] [--json] [--pass NAME]...
                          [--list] [--mutate NAME]

Exit codes (the repo-wide convention, see raft_tpu/__main__.py):
  0   clean (no errors; warnings allowed without --strict)
  3   findings: any error, or any warning under --strict
  64  usage error (unknown flag / pass / mutation)

``--pass NAME`` restricts the run (repeatable); ``--list`` prints the
pass catalogue; ``--mutate NAME`` applies one seeded contract
violation from the self-test kit and runs the targeted pass — the
negative control proving the auditor fires (expected exit: 3).
``--json`` emits one machine-readable document on stdout (the same
shape bench.py embeds as the lint provenance verdict).
"""

from __future__ import annotations

import json
import sys
import time

from . import donation, events_drift, guard_purity, lanes, signatures, sync
from .selftest import MUTATIONS, PASS_OF

PASSES = {
    "donation": donation.run,
    "signatures": signatures.run,
    "guard-purity": guard_purity.run,
    "hidden-sync": sync.run,
    "lane-discipline": lanes.run,
    "events-drift": events_drift.run,
}


def run_lint(pass_names=None, pass_kwargs=None):
    """Run the selected passes (all, in catalogue order, by default);
    returns the list of PassResult."""
    names = tuple(pass_names) if pass_names else tuple(PASSES)
    kwargs = pass_kwargs or {}
    return [PASSES[n](**kwargs.get(n, {})) for n in names]


def exit_code(results, strict: bool) -> int:
    errors = sum(r.errors for r in results)
    warnings = sum(r.warnings for r in results)
    if errors or (strict and warnings):
        return 3
    return 0


def verdict(results, strict: bool) -> dict:
    """The machine-readable summary (bench.py provenance block)."""
    return {
        "strict": strict,
        "errors": sum(r.errors for r in results),
        "warnings": sum(r.warnings for r in results),
        "checked": sum(r.checked for r in results),
        "clean": exit_code(results, strict) == 0,
        "passes": [r.to_dict() for r in results],
    }


def lint_verdict(strict: bool = True) -> dict:
    """One-call in-process lint for tooling (bench.py): all passes,
    verdict dict."""
    return verdict(run_lint(), strict)


def _usage(msg: str) -> int:
    print(f"raft_tpu lint: {msg}", file=sys.stderr)
    print(__doc__, file=sys.stderr)
    return 64


def lint_main(argv) -> int:
    strict = as_json = list_only = False
    chosen: list = []
    mutate = None
    it = iter(argv)
    for a in it:
        if a == "--strict":
            strict = True
        elif a == "--json":
            as_json = True
        elif a == "--list":
            list_only = True
        elif a == "--pass":
            name = next(it, None)
            if name is None or name not in PASSES:
                return _usage(
                    f"--pass expects one of {', '.join(PASSES)}")
            chosen.append(name)
        elif a == "--mutate":
            mutate = next(it, None)
            if mutate is None or mutate not in MUTATIONS:
                return _usage(
                    f"--mutate expects one of {', '.join(MUTATIONS)}")
        else:
            return _usage(f"unknown argument {a!r}")

    if list_only:
        for name in PASSES:
            doc = (sys.modules[PASSES[name].__module__].__doc__ or "")
            head = doc.strip().splitlines()[0] if doc.strip() else ""
            print(f"{name:16s} {head}")
        if not as_json:
            return 0

    t0 = time.time()
    if mutate is not None:
        target = PASS_OF[mutate]
        if chosen and target not in chosen:
            return _usage(
                f"--mutate {mutate} targets pass '{target}', which "
                f"--pass excluded")
        with MUTATIONS[mutate]() as kw:
            results = run_lint((target,), {target: kw})
    else:
        results = run_lint(chosen or None)

    if as_json:
        print(json.dumps(verdict(results, strict), indent=2))
    else:
        n_findings = 0
        for r in results:
            status = "clean" if not r.findings else (
                f"{r.errors} error(s), {r.warnings} warning(s)")
            print(f"[{r.pass_id}] checked {r.checked} in "
                  f"{r.seconds:.1f}s: {status}")
            for note in r.notes:
                print(f"    note: {note}")
            for f in r.findings:
                n_findings += 1
                print(f"  {f.render()}")
        rc = exit_code(results, strict)
        label = "MUTATION " + mutate if mutate else "lint"
        print(f"{label}: {n_findings} finding(s) across "
              f"{len(results)} pass(es) in {time.time() - t0:.1f}s -> "
              f"exit {rc}")
    return exit_code(results, strict)
