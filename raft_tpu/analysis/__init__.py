"""Static analysis: the kernel contract auditor behind ``raft_tpu lint``.

The survey's north star — every variant's ``Next`` relation hand-lowered
to fused, donated, fixed-signature device programs — rests on contracts
no type system sees: wave programs must alias their capacity-shaped
carries, deep runs must stay on a closed set of precompiled signatures,
guard passes must write no W-wide successor rows, wave loops must stay
zero-extra-sync, and fleet-packable guards must reach dynamic constants
through the ``_cv`` lane indirection. Each pass in this package proves
one of those contracts across the model registry WITHOUT executing a
wave, and anchors every violation to a ``file:line`` so a refactor that
breaks a contract is named before it is benchmarked.

Passes (see ``cli.PASSES``):

  donation        input-output aliasing of every wave/stage/merge jit
  signatures      retrace-closure of the geometry state machine
  guard-purity    DCE-derived guard passes write no W-wide rows
  hidden-sync     no device syncs inside chunk/wave loops
  lane-discipline ``_cv`` constant reads + ACTION_NAMES lock-step
  events-drift    metrics schema rules vs DECLARED_EVENTS

Entry point: ``python -m raft_tpu lint [--strict] [--json] [--pass NAME]``
(exit 0 clean, 3 findings under --strict, 64 usage — the repo's stable
exit-code contract). ``--mutate NAME`` applies one seeded contract
violation and re-runs the targeted pass: the self-test that proves each
auditor actually fires.
"""

from .findings import Finding, PassResult  # noqa: F401
