"""Seeded-mutation self-test kit: prove each lint pass actually fires.

A linter that never fails is indistinguishable from one that audits
nothing, so each pass ships with one seeded contract violation —
applied as a reversible in-process patch (class attributes, pass
hooks, or source-text overrides), never touching the working tree —
and the CLI's ``--mutate NAME`` re-runs the targeted pass under it.
The acceptance contract: every mutation exits 3 with a finding naming
the pass and a ``file:line``.

  undonated-carry   drop the cov carry from DeviceBFS.WAVE_DONATE
  open-signature    skew _seen_size_for off the precompiled ladder
  wide-guard-write  leak a W-wide block into a kept guard output
  injected-sync     insert a jax.device_get inside the wave loop
  raw-const-read    read a FLEET_DYN constant around the _cv lane
"""

from __future__ import annotations

import contextlib
import os

PASS_OF = {
    "undonated-carry": "donation",
    "open-signature": "signatures",
    "wide-guard-write": "guard-purity",
    "injected-sync": "hidden-sync",
    "raw-const-read": "lane-discipline",
}


@contextlib.contextmanager
def undonated_carry():
    """Un-donate the coverage carry of the fused wave program: the
    classic regression (a donate tuple losing an argnum), caught by the
    donation auditor's independent carries map."""
    from ..checker.device_bfs import DeviceBFS

    orig = DeviceBFS.WAVE_DONATE
    DeviceBFS.WAVE_DONATE = tuple(a for a in orig if a != 7)
    try:
        yield {"families": ("raft",), "scopes": ("device",)}
    finally:
        DeviceBFS.WAVE_DONATE = orig


@contextlib.contextmanager
def open_signature():
    """Skew the runtime merge-target chooser off the precompiled
    ladder — the BENCH_r05 retrace cliff, reintroduced."""
    from ..checker.device_bfs import DeviceBFS

    orig = DeviceBFS._seen_size_for

    def skewed(self, n):
        return orig(self, n) + 3

    DeviceBFS._seen_size_for = skewed
    try:
        yield {"families": ("raft",)}
    finally:
        DeviceBFS._seen_size_for = orig


@contextlib.contextmanager
def wide_guard_write():
    """Let a W-wide block survive guard DCE: a fresh (never-cached)
    model whose ``_expand1`` threads a [2, W] intermediate into a kept
    guard output, so the derived guard jaxpr materializes it."""
    from . import guard_purity, registry

    def poisoned(fam):
        import jax.numpy as jnp

        m = registry.fresh_tiny_model(fam)
        orig_expand = type(m)._expand1

        def bad_expand(s):
            succs, valid, rank, ovf = orig_expand(m, s)
            wide = jnp.broadcast_to(s[None, :], (2, s.shape[0]))
            leak = wide.sum().astype(rank.dtype)
            return succs, valid, rank + leak * 0, ovf

        m.__dict__["_expand1"] = bad_expand
        return m

    orig = guard_purity.MODEL_FN
    guard_purity.MODEL_FN = poisoned
    try:
        yield {"families": ("raft",)}
    finally:
        guard_purity.MODEL_FN = orig


@contextlib.contextmanager
def injected_sync():
    """Insert a per-wave-loop jax.device_get into a COPY of the
    DeviceBFS source (the tree is untouched) and point the sync pass's
    source override at it."""
    from . import sync
    from .findings import REPO_ROOT

    relpath = os.path.join("raft_tpu", "checker", "device_bfs.py")
    with open(os.path.join(REPO_ROOT, relpath)) as fh:
        src = fh.read()
    anchor = "\n            depth += 1\n"
    assert anchor in src, "mutation anchor vanished from DeviceBFS.run"
    mutated = src.replace(
        anchor,
        "\n            depth += 1\n"
        "            _ = jax.device_get(viol)\n",
        1,
    )
    assert mutated != src
    orig = sync.SOURCE_OVERRIDES
    sync.SOURCE_OVERRIDES = {relpath: mutated}
    try:
        yield {}
    finally:
        sync.SOURCE_OVERRIDES = orig


@contextlib.contextmanager
def raw_const_read():
    """Bypass the ``_cv`` lane for one FLEET_DYN constant in a COPY of
    the raft lowering and point the lane pass's override at it."""
    from . import lanes
    from .findings import REPO_ROOT

    relpath = os.path.join("raft_tpu", "models", "raft.py")
    with open(os.path.join(REPO_ROOT, relpath)) as fh:
        src = fh.read()
    good = 'self._cv(d, "max_restarts")'
    assert good in src, "mutation anchor vanished from models/raft.py"
    mutated = src.replace(good, "self.p.max_restarts", 1)
    orig = lanes.SOURCE_OVERRIDES
    lanes.SOURCE_OVERRIDES = {relpath: mutated}
    try:
        yield {}
    finally:
        lanes.SOURCE_OVERRIDES = orig


MUTATIONS = {
    "undonated-carry": undonated_carry,
    "open-signature": open_signature,
    "wide-guard-write": wide_guard_write,
    "injected-sync": injected_sync,
    "raw-const-read": raw_const_read,
}
