"""Events-drift check: the metrics schema's three sources of truth —
``DECLARED_EVENTS``, the validator branches, and the dependency-free
``scripts/check_metrics_schema.py`` contract doc — cannot drift apart.

``obs/events.py`` declares the event vocabulary (``DECLARED_EVENTS``
-> ``EVENT_KEYS``) and validates structurally per type;
``scripts/check_metrics_schema.py`` is the CI-facing wrapper whose
module docstring IS the published schema contract. Three drift modes,
each caught here:

  * a validator branch tests an event type that is no longer declared
    (stale branch: dead validation that reads as coverage) — error;
  * a declared type has no mention in the schema script's contract doc
    (the doc silently under-promises; consumers building on the doc
    miss the event) — warning, gates under --strict;
  * a documented-looking type in a validator membership test that the
    declaration table dropped — same error as the first mode.

Mentions are matched on WORD BOUNDARIES: "shard_stall" must not mask a
missing "stall" entry.
"""

from __future__ import annotations

import ast
import os
import re
import time

from .findings import Finding, PassResult, rel

PASS_ID = "events-drift"

SCHEMA_SCRIPT = os.path.join("scripts", "check_metrics_schema.py")
EVENTS_MODULE = os.path.join("raft_tpu", "obs", "events.py")


def branch_literals(src: str):
    """String literals the validators compare an event type against:
    ``etype == "wave"`` / ``etype in ("resume", ...)`` patterns inside
    ``validate_event`` / ``validate_lines``. Returns {literal: line}."""
    tree = ast.parse(src)
    out: dict[str, int] = {}
    for fn in ast.walk(tree):
        if not (isinstance(fn, ast.FunctionDef)
                and fn.name in ("validate_event", "validate_lines")):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            if not (isinstance(node.left, ast.Name)
                    and node.left.id == "etype"):
                continue
            for op, cmp in zip(node.ops, node.comparators):
                if isinstance(op, ast.Eq) and isinstance(
                        cmp, ast.Constant) and isinstance(cmp.value, str):
                    out.setdefault(cmp.value, node.lineno)
                elif isinstance(op, ast.In) and isinstance(cmp, ast.Tuple):
                    for e in cmp.elts:
                        if isinstance(e, ast.Constant) and isinstance(
                                e.value, str):
                            out.setdefault(e.value, node.lineno)
    return out


def missing_doc_mentions(doc: str, declared) -> list[str]:
    """Declared event types with no word-boundary mention in ``doc``."""
    return sorted(
        t for t in declared
        if not re.search(rf"\b{re.escape(t)}\b", doc)
    )


def run() -> PassResult:
    from .findings import REPO_ROOT
    from ..obs.events import EVENT_KEYS

    t0 = time.time()
    findings: list[Finding] = []
    declared = set(EVENT_KEYS)

    with open(os.path.join(REPO_ROOT, EVENTS_MODULE)) as fh:
        events_src = fh.read()
    literals = branch_literals(events_src)
    for lit, line in sorted(literals.items()):
        if lit not in declared:
            findings.append(Finding(
                PASS_ID, "error", EVENTS_MODULE, line,
                f"validator branch tests event type '{lit}' which "
                f"DECLARED_EVENTS no longer declares — stale branch "
                f"reads as coverage",
                {"type": lit, "declared": sorted(declared)},
            ))

    with open(os.path.join(REPO_ROOT, SCHEMA_SCRIPT)) as fh:
        script_src = fh.read()
    doc = ast.get_docstring(ast.parse(script_src)) or ""
    for t in missing_doc_mentions(doc, declared):
        findings.append(Finding(
            PASS_ID, "warning", SCHEMA_SCRIPT, 1,
            f"declared event type '{t}' is never mentioned in the "
            f"schema contract doc — the published contract silently "
            f"under-promises",
            {"type": t},
        ))

    checked = len(declared) + len(literals)
    notes = [f"{len(declared)} declared types vs {len(literals)} "
             f"validator branches + contract doc"]
    return PassResult(PASS_ID, findings, checked, time.time() - t0, notes)
