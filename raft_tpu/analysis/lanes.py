"""Lane-discipline pass: fleet-packable constants are read through the
``_cv`` lane indirection, and ACTION_NAMES stays lock-stepped with each
module's rank table.

Fleet packing (fleet/grouping.py FLEET_DYN) compiles ONE program for a
whole grid of CONSTANTS bindings by routing each dynamic constant
through a per-state lane: guards call ``self._cv(d, "max_restarts")``,
which reads the ``c_max_restarts`` lane when the layout packs one and
falls back to the scalar param otherwise. A guard that reads
``self.p.max_restarts`` (or the params property) directly compiles the
constant INTO the program — every job in a packed fleet group then
silently checks the first job's bound, with no shape error to catch it.
This pass AST-scans the FLEET_DYN model modules and flags any attribute
read of a dynamic-constant name inside a function that receives packed
state (a ``d``/``states`` argument).

The second contract is the coverage registry lock-step (migrated from
tests/test_action_coverage.py): each model module's widest
``(R_A, R_B, ...) = range(N)`` rank unpack, plus its extension
constants, must agree with ``len(ACTION_NAMES)`` — a new Next disjunct
without a name breaks coverage attribution silently.
"""

from __future__ import annotations

import ast
import importlib
import os
import time

from .findings import Finding, PassResult, rel

PASS_ID = "lane-discipline"

# the hook the mutation self-test overrides: {rel_path: source_text}
SOURCE_OVERRIDES: dict | None = None

# params-class -> module resolution for FLEET_DYN (grouping keys params
# classes; the guards live in the model modules)
_DYN_MODULES = {"RaftParams": "raft", "PullRaftParams": "pull_raft"}


def module_max_rank(src: str) -> int | None:
    """Highest action rank a model module declares, read from source:
    the widest ``(R_A, ...) = range(N)`` unpack (>= 10 targets, the
    Next-disjunct order) extended by later constant assigns whose
    values continue the numbering."""
    n_base = None
    extras: list[int] = []
    for node in ast.parse(src).body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt, val = node.targets[0], node.value
        if (
            isinstance(tgt, ast.Tuple) and len(tgt.elts) >= 10
            and isinstance(val, ast.Call)
            and isinstance(val.func, ast.Name) and val.func.id == "range"
            and len(val.args) == 1
            and isinstance(val.args[0], ast.Constant)
        ):
            n_base = int(val.args[0].value)
            if len(tgt.elts) != n_base:
                return None  # arity mismatch: reported by the caller
            extras = []
        elif (
            n_base is not None
            and isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple)
            and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in val.elts
            )
        ):
            vals = [int(e.value) for e in val.elts]
            if vals and min(vals) >= n_base:
                extras += vals
    if n_base is None:
        return None
    return max([n_base - 1, *extras])


def _packed_state_functions(tree: ast.Module):
    """FunctionDefs (at any nesting) that touch packed state — a ``d``
    or ``states`` argument, or a ``d = self._dec(...)``-style local
    decode — i.e. the fleet-packable guard/apply surface where
    constants must route through ``_cv``."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        names = {a.arg for a in node.args.args}
        if "d" in names or "states" in names:
            yield node
            continue
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and sub.targets[0].id in ("d", "states")
                    and isinstance(sub.value, ast.Call)):
                yield node
                break


def scan_dyn_consts(src: str, path: str, dyn_names, findings: list) -> int:
    """Flag raw attribute reads of dynamic-constant names inside
    packed-state functions; returns functions audited. The compliant
    spelling passes the name as a STRING to ``_cv``/``_cv_batch``, so
    any ``<expr>.max_restarts`` attribute inside such a function is a
    compiled-in constant."""
    tree = ast.parse(src)
    audited = 0
    for fn in _packed_state_functions(tree):
        audited += 1
        for node in ast.walk(fn):
            if (isinstance(node, ast.Attribute)
                    and node.attr in dyn_names):
                findings.append(Finding(
                    PASS_ID, "error", path, node.lineno,
                    f"{fn.name}() reads dynamic constant "
                    f"'{node.attr}' as an attribute — in a packed "
                    f"fleet group every job would check the compiled "
                    f"job's bound; route it through "
                    f"self._cv(d, \"{node.attr}\")",
                    {"function": fn.name, "constant": node.attr},
                ))
    return audited


def _module_source(mod_name: str) -> tuple[str, str]:
    relpath = os.path.join("raft_tpu", "models", f"{mod_name}.py")
    if SOURCE_OVERRIDES and relpath in SOURCE_OVERRIDES:
        return SOURCE_OVERRIDES[relpath], relpath
    mod = importlib.import_module(f"raft_tpu.models.{mod_name}")
    with open(mod.__file__) as fh:
        return fh.read(), rel(mod.__file__)


def run() -> PassResult:
    from ..fleet.grouping import FLEET_DYN
    from . import registry

    t0 = time.time()
    findings: list[Finding] = []
    checked = 0

    # _cv discipline over the fleet-packable modules
    for cls_name, dyn_names in sorted(FLEET_DYN.items()):
        mod_name = _DYN_MODULES.get(cls_name)
        if mod_name is None:
            findings.append(Finding(
                PASS_ID, "warning", "raft_tpu/fleet/grouping.py", 1,
                f"FLEET_DYN class {cls_name} has no known model module "
                f"— the lane-discipline audit cannot see its guards",
                {"class": cls_name},
            ))
            continue
        src, path = _module_source(mod_name)
        checked += scan_dyn_consts(src, path, set(dyn_names), findings)

    # ACTION_NAMES lock-step across every model module
    for mod_name in registry.MODEL_MODULES:
        checked += 1
        src, path = _module_source(mod_name)
        max_rank = module_max_rank(src)
        mod = importlib.import_module(f"raft_tpu.models.{mod_name}")
        names = getattr(mod, "ACTION_NAMES", None)
        if max_rank is None or names is None:
            findings.append(Finding(
                PASS_ID, "error", path, 1,
                f"{mod_name}: no rank table / ACTION_NAMES found — the "
                f"coverage registry contract expects both",
                {"module": mod_name},
            ))
        elif len(names) != max_rank + 1:
            findings.append(Finding(
                PASS_ID, "error", path, 1,
                f"{mod_name}: {len(names)} ACTION_NAMES for declared "
                f"ranks 0..{max_rank} — coverage attribution breaks "
                f"silently on the drifted ranks",
                {"module": mod_name, "names": len(names),
                 "max_rank": max_rank},
            ))
    notes = [f"{len(registry.MODEL_MODULES)} modules lock-step, "
             f"{len(FLEET_DYN)} packable families"]
    return PassResult(PASS_ID, findings, checked, time.time() - t0, notes)
