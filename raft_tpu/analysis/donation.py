"""Donation auditor: prove every capacity-shaped loop carry aliases an
output of the lowered program that rebinds it.

The engines declare their dispatch surface via ``audit_programs()``
(DeviceBFS: fused wave + --timeline stages + seen-ladder merges;
ShardedBFS: shard_map chunk + timeline pre/exchange/post; RunLSM: the
cascade merge closure). Each entry carries an INDEPENDENT ``carries``
map — written out separately from the ``*_DONATE`` tuples the jits
consume — so dropping an argnum from a donate tuple (the classic
regression: PR 9 found an undonated stage dispatch costing 74.2 s vs
0.105 s) diverges the declaration from the lowering and is reported
here with the analytic bytes copied per wave.

The proof reads the LOWERED computation, not the python: jax marks
input-output aliasing in the StableHLO ``@main`` signature as
``{tf.aliasing_output = K}`` arg attributes. A carry must carry that
attribute whenever a shape/dtype-compatible output slot exists for it
(a donated input whose shape matches no remaining output — e.g. a
ladder run consumed by a pad-up merge — cannot alias anything and is
exempt: donation still releases its buffer, but no copy is saved).

Coverage vs budget: the full device + sharded + LSM surface is lowered
for one family (raft); for the other five families the fused wave
program — the only per-wave dispatch on the hot path — is lowered and
audited, so a model whose lowering defeats aliasing is still caught.
"""

from __future__ import annotations

import re
import time

from .findings import Finding, PassResult

PASS_ID = "donation"

# the family whose complete program surface is lowered; the rest get
# the wave program only (lowering is the entire cost of this pass)
FULL_FAMILY = "raft"

_ARG_RE = re.compile(r"%arg(\d+): tensor<([^>]+)>")
_ALIAS_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")
_DTYPE_BYTES = {
    "i1": 1, "i8": 1, "ui8": 1, "i16": 2, "ui16": 2, "i32": 4,
    "ui32": 4, "i64": 8, "ui64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "f64": 8,
}


def parse_main_aliasing(txt: str):
    """Parse the ``@main`` signature of lowered StableHLO text into
    ``(args, results)``: ``args`` maps argnum -> (type, aliased output
    index or None), ``results`` is the list of output type strings.
    Type strings are the tensor bodies, e.g. ``"5120x82xi32"``."""
    i = txt.index("@main(")
    j = txt.index(") -> ", i)
    argstr = txt[i + len("@main("):j]
    resstr = txt[j:txt.index("\n", j)]
    args = {}
    for part in re.split(r"(?=%arg\d+)", argstr):
        m = _ARG_RE.match(part)
        if not m:
            continue
        am = _ALIAS_RE.search(part)
        args[int(m.group(1))] = (
            m.group(2), int(am.group(1)) if am else None)
    results = re.findall(r"tensor<([^>]+)>", resstr)
    return args, results


def tensor_bytes(type_str: str) -> int:
    """Byte size of a StableHLO tensor type body ('5120x82xi32')."""
    parts = type_str.split("x")
    dtype = parts[-1]
    n = 1
    for p in parts[:-1]:
        n *= int(p)
    return n * _DTYPE_BYTES.get(dtype, 8)


def audit_entry(entry: dict, scope: str, findings: list) -> None:
    """Lower one audit entry and check its declared carries/pins
    against the ``tf.aliasing_output`` attributes in the result."""
    import warnings

    with warnings.catch_warnings():
        # alias-impossible donations (pad-up merges, CPU truncate-
        # merges) warn at lowering; the span check below reasons about
        # them explicitly
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        txt = entry["fn"].lower(*entry["args"]).as_text()
    args, results = parse_main_aliasing(txt)
    path, line = entry["site"]
    # output slots by type, minus the slots aliased args already consume
    avail: dict[str, int] = {}
    for ty in results:
        avail[ty] = avail.get(ty, 0) + 1
    for ty, tgt in args.values():
        if tgt is not None:
            avail[ty] = avail.get(ty, 0) - 1
    for argnum, name in sorted(entry["carries"].items()):
        if argnum not in args:
            findings.append(Finding(
                PASS_ID, "error", path, line,
                f"{scope} program '{entry['name']}': declared carry "
                f"'{name}' (arg {argnum}) is missing from the lowered "
                f"signature — audit surface out of date",
                {"program": entry["name"], "arg": argnum},
            ))
            continue
        ty, tgt = args[argnum]
        if tgt is not None:
            continue  # aliased: the contract holds
        if avail.get(ty, 0) <= 0:
            # no compatible output slot remains — aliasing is
            # impossible for this carry (e.g. ladder runs folded into
            # a pad-up merge); donation still frees the buffer
            continue
        avail[ty] -= 1
        per_wave = entry.get("per_wave", 1)
        findings.append(Finding(
            PASS_ID, "error", path, line,
            f"{scope} program '{entry['name']}': carry '{name}' "
            f"(arg {argnum}, tensor<{ty}>) is NOT donated — every "
            f"dispatch copies it through the output",
            {
                "program": entry["name"], "arg": argnum,
                "tensor": ty,
                "bytes_per_wave": tensor_bytes(ty) * per_wave,
            },
        ))
    for argnum, name in sorted(entry.get("pinned", {}).items()):
        if argnum in args and args[argnum][1] is not None:
            findings.append(Finding(
                PASS_ID, "error", path, line,
                f"{scope} program '{entry['name']}': pinned buffer "
                f"'{name}' (arg {argnum}) IS donated — the host reuses "
                f"it after the dispatch (use-after-donate)",
                {"program": entry["name"], "arg": argnum},
            ))


def run(families=None, scopes=("device", "sharded", "lsm")) -> PassResult:
    from . import registry

    t0 = time.time()
    families = tuple(families) if families else registry.FAMILIES
    findings: list[Finding] = []
    notes: list[str] = []
    checked = 0

    full = FULL_FAMILY if FULL_FAMILY in families else families[0]
    if "device" in scopes:
        for fam in families:
            eng = registry.device_engine(fam)
            for entry in eng.audit_programs():
                if fam != full and entry["name"] != "wave":
                    continue
                audit_entry(entry, f"device:{fam}", findings)
                checked += 1
        notes.append(
            f"device: full surface for {full}, wave program for "
            f"{len(families) - 1} other families")
    if "sharded" in scopes:
        sh = registry.sharded_engine(full)
        for entry in sh.audit_programs():
            audit_entry(entry, f"sharded:{full}", findings)
            checked += 1
        if "lsm" in scopes:
            for entry in sh._lsm.audit_programs():
                audit_entry(entry, f"lsm:{full}", findings)
                checked += 1
        notes.append(f"sharded+lsm surface for {full} (D=1 mesh)")

    return PassResult(
        PASS_ID, findings, checked, time.time() - t0, notes)
