"""Signature-closure auditor: prove a deep run dispatches only
precompiled program signatures — the retrace-cliff class, symbolically.

The BENCH_r05 depth-32 cliff was one mid-run compile: a seen merge
whose target outgrew the concat total left a non-ladder-size run, and
the next wave retraced the whole wave program at a never-precompiled
shape (~117 s of a 152.6 s wave). The engine now precompiles exactly
``DeviceBFS.signature_inventory()``; this pass independently recomputes
the REACHABLE signature set from the geometry primitives and proves the
two are equal:

  * ladder well-formedness — ``_seen_sizes`` strictly increasing powers
    of two ending at TOPSZ (= pow2 ceiling of max_seen_cap);
  * dispatch closure — ``_seen_size_for`` (the runtime target chooser)
    probed at every ladder boundary +/-1 must return exactly the
    first-size-at-least member the ladder implies, always inside the
    precompiled wave set, and overflow past TOPSZ must raise;
  * merge closure — the precompiled merge keys must cover every
    (size, target >= size) pair at the wave-ladder shapes;
  * pad-up proof — ``eval_shape`` of every merge spec body returns
    EXACTLY ``(target,)`` u64 (the shape invariant whose violation
    caused the cliff);
  * growth chain — ``next_cap`` frontier/journal growth from the
    current capacity terminates at the cap ceiling in finitely many
    chunk-aligned steps (growth retraces are bounded and precompilable);
  * sharded arity — RunLSM pre-creates its full ladder, so the chunk
    program's run-tuple arity can never change mid-run;
  * fleet grouping — FLEET_DYN names resolve to real params fields
    (a renamed field would silently split or mis-merge fleet groups).
"""

from __future__ import annotations

import time

from .findings import Finding, PassResult, site_of

PASS_ID = "signatures"


def _expected_first_geq(n: int, sizes) -> int | None:
    for s in sizes:
        if n <= s:
            return s
    return None


def _check_device(fam: str, eng, findings: list) -> int:
    import jax
    import jax.numpy as jnp

    checked = 0
    cls = type(eng)
    path, line = site_of(cls._seen_size_for)
    sizes = tuple(eng._seen_sizes)

    # ladder well-formedness
    checked += 1
    ok = (
        len(sizes) > 0
        and all(s > 0 and (s & (s - 1)) == 0 for s in sizes)
        and all(a < b for a, b in zip(sizes, sizes[1:]))
        and sizes[-1] == eng.TOPSZ
    )
    if not ok:
        findings.append(Finding(
            PASS_ID, "error", path, line,
            f"device:{fam}: malformed seen ladder {sizes} "
            f"(TOPSZ={eng.TOPSZ}) — must be strictly increasing powers "
            f"of two ending at TOPSZ",
            {"sizes": list(sizes), "topsz": eng.TOPSZ},
        ))
        return checked  # downstream checks assume the ladder

    inv = list(eng.signature_inventory())
    wave_set = [s for tag, *rest in inv if tag == "wave" for s in rest]
    merge_set = {tuple(sig[1:]) for sig in inv if sig[0] == "merge"}

    # precompiled wave set == the ladder, exactly
    checked += 1
    if wave_set != list(sizes):
        findings.append(Finding(
            PASS_ID, "error", path, line,
            f"device:{fam}: precompiled wave signatures {wave_set} != "
            f"seen ladder {list(sizes)}",
            {"inventory": wave_set, "ladder": list(sizes)},
        ))

    # dispatch closure: probe the runtime target chooser at every
    # boundary; it must agree with the independent first-geq rule and
    # stay inside the precompiled set
    probes = {1}
    for s in sizes:
        probes.update(x for x in (s - 1, s, s + 1) if 1 <= x <= eng.TOPSZ)
    for n in sorted(probes):
        checked += 1
        got = eng._seen_size_for(n)
        want = _expected_first_geq(n, sizes)
        if got != want or got not in wave_set:
            findings.append(Finding(
                PASS_ID, "error", path, line,
                f"device:{fam}: _seen_size_for({n}) -> {got}, outside "
                f"the precompiled set (expected {want}) — a deep run "
                f"dispatching this target retraces mid-run",
                {"n": n, "got": got, "expected": want,
                 "precompiled": wave_set},
            ))
    checked += 1
    try:
        eng._seen_size_for(eng.TOPSZ + 1)
        findings.append(Finding(
            PASS_ID, "error", path, line,
            f"device:{fam}: _seen_size_for(TOPSZ+1) did not raise — the "
            f"capacity guard would dispatch an unprecompiled signature",
        ))
    except OverflowError:
        pass

    # merge closure at the wave-ladder shapes
    K = 0
    while (eng.R0 << K) < _pow2_at_least(eng.FCAP):
        K += 1
    lshapes = tuple(eng.R0 << i for i in range(K + 1))
    expect_merges = {
        (s, lshapes, t) for si, s in enumerate(sizes)
        for t in sizes[si:]
    }
    checked += 1
    if merge_set != expect_merges:
        mpath, mline = site_of(cls.signature_inventory)
        findings.append(Finding(
            PASS_ID, "error", mpath, mline,
            f"device:{fam}: precompiled merge signatures differ from "
            f"the reachable (size, target>=size) closure at ladder "
            f"shapes {lshapes}",
            {"missing": sorted(
                str(k) for k in expect_merges - merge_set),
             "extra": sorted(str(k) for k in merge_set - expect_merges)},
        ))

    # pad-up proof: the merge body's output shape is EXACTLY (target,)
    spath, sline = site_of(cls._seen_merge_spec)
    for key in sorted(merge_set):
        checked += 1
        size, lsh, target = key
        body, _donate = eng._seen_merge_spec(key)
        out = jax.eval_shape(*(
            (body,)
            + (jax.ShapeDtypeStruct((size,), jnp.uint64),)
            + tuple(jax.ShapeDtypeStruct((n,), jnp.uint64) for n in lsh)
        ))
        if out.shape != (target,) or out.dtype != jnp.uint64:
            findings.append(Finding(
                PASS_ID, "error", spath, sline,
                f"device:{fam}: merge {key} produces shape {out.shape} "
                f"instead of exactly ({target},) — the next wave would "
                f"retrace at a never-precompiled seen size",
                {"key": str(key), "out_shape": list(out.shape)},
            ))

    # growth chains terminate at the ceiling in chunk-aligned steps
    gpath, gline = site_of(cls._maybe_grow)
    for what, cur, ceil in (
        ("frontier", eng.FCAP, eng.MAX_FCAP),
        ("journal", eng.JCAP, eng.MAX_JCAP),
    ):
        checked += 1
        steps = 0
        bad = None
        while cur < ceil:
            new = eng._next_cap(cur * eng.GROWTH, cur, ceil, eng.GROWTH,
                                eng.chunk)
            if new <= cur or new > ceil or new % eng.chunk:
                bad = f"step {cur} -> {new}"
                break
            cur = new
            steps += 1
            if steps > 64:
                bad = f"no convergence after {steps} steps"
                break
        if bad:
            findings.append(Finding(
                PASS_ID, "error", gpath, gline,
                f"device:{fam}: {what} growth chain is not a finite "
                f"chunk-aligned ascent to the cap ceiling ({bad})",
                {"what": what, "ceiling": ceil},
            ))
    checked += 1
    if eng.FCAP % eng.chunk:
        findings.append(Finding(
            PASS_ID, "error", path, line,
            f"device:{fam}: FCAP {eng.FCAP} not a multiple of chunk "
            f"{eng.chunk} — the chunk schedule would dispatch a ragged "
            f"tail signature",
        ))
    return checked


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _check_sharded(fam: str, sh, findings: list) -> int:
    lsm = sh._lsm
    path, line = site_of(type(lsm).add_level)
    checked = 1
    n = lsm.n_levels()
    if n != lsm._init_levels or lsm.lv_size(n - 1) < lsm.TOPSZ:
        findings.append(Finding(
            PASS_ID, "error", path, line,
            f"sharded:{fam}: LSM ladder of {n} levels does not reach "
            f"TOPSZ={lsm.TOPSZ} at construction — add_level mid-run "
            f"changes the chunk program arity (a whole retrace)",
            {"levels": n, "top": lsm.lv_size(n - 1), "topsz": lsm.TOPSZ},
        ))
    return checked


def _check_fleet(findings: list) -> int:
    import dataclasses
    import importlib

    from ..fleet import grouping

    path, line = site_of(grouping._group_key)
    checked = 0
    for cls_name, names in grouping.FLEET_DYN.items():
        checked += 1
        mod = "raft" if cls_name == "RaftParams" else "pull_raft"
        params_cls = getattr(
            importlib.import_module(f"raft_tpu.models.{mod}"), cls_name)
        fields = {f.name for f in dataclasses.fields(params_cls)}
        missing = [n for n in names if n not in fields]
        if missing:
            findings.append(Finding(
                PASS_ID, "error", path, line,
                f"FLEET_DYN[{cls_name}] names {missing} are not fields "
                f"of {cls_name} — fleet grouping would mis-merge jobs",
                {"class": cls_name, "missing": missing},
            ))
    return checked


def run(families=None) -> PassResult:
    from . import registry

    t0 = time.time()
    families = tuple(families) if families else registry.FAMILIES
    findings: list[Finding] = []
    checked = 0
    for fam in families:
        checked += _check_device(fam, registry.device_engine(fam),
                                 findings)
    checked += _check_sharded(
        families[0], registry.sharded_engine(families[0]), findings)
    checked += _check_fleet(findings)
    notes = [f"{len(families)} device ladders + sharded arity + "
             f"fleet grouping"]
    return PassResult(PASS_ID, findings, checked, time.time() - t0, notes)
