"""The audit registry: tiny-constants instances of all six spec
lowerings plus engine factories at lint geometry.

The passes prove contracts on LOWERINGS, not runs, so the constants are
the smallest that exercise every structural feature (the same bindings
tests/test_expand_sparse.py sweeps). Models are cached per lint process
(``cached_model`` shares jitted kernels with the test suite); engines
are built fresh per pass — construction traces nothing beyond the
wave/chunk jit wrappers.

Lint engine geometry: capacities small enough that program LOWERING (the
only cost a pass pays) stays in the tier-1 smoke budget, while keeping
every structural element real — a multi-size seen ladder, VC pad rows,
a canon memo, the binary-counter wave ladder.
"""

from __future__ import annotations

import importlib

# family -> (models submodule, params builder kwargs) — tiny constants,
# one binding per spec lowering, mirroring tests/test_expand_sparse.py
FAMILY_PARAMS = {
    "raft": ("raft", "RaftParams", dict(
        n_servers=2, n_values=2, max_elections=2, max_restarts=0,
        msg_slots=16,
    )),
    "pull_raft": ("pull_raft", "PullRaftParams", dict(
        n_servers=3, n_values=1, max_elections=2, max_restarts=0,
        msg_slots=24,
    )),
    "kraft": ("kraft", "KRaftParams", dict(
        n_servers=3, n_values=1, max_elections=2, max_restarts=0,
        msg_slots=24,
    )),
    "joint_raft": ("joint_raft", "JointRaftParams", dict(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=1,
        max_restarts=0, max_reconfigs=1, max_values_per_term=1,
        reconfig_type=2, msg_slots=64,
    )),
    "reconfig_raft": ("reconfig_raft", "ReconfigRaftParams", dict(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=1,
        max_restarts=0, max_values_per_term=1, max_add_reconfigs=1,
        max_remove_reconfigs=1, min_cluster_size=2, max_cluster_size=3,
        msg_slots=64,
    )),
    "kraft_reconfig": ("kraft_reconfig", "KRaftReconfigParams", dict(
        n_hosts=3, n_values=1, init_cluster_size=2, min_cluster_size=2,
        max_cluster_size=3, max_elections=1, max_restarts=1,
        max_values_per_epoch=1, max_add_reconfigs=1,
        max_remove_reconfigs=1, max_spawned_servers=4, msg_slots=24,
    )),
}

FAMILIES = tuple(FAMILY_PARAMS)

# the same module set the ACTION_NAMES lock-step contract spans
MODEL_MODULES = (
    "raft", "kraft", "pull_raft", "kraft_reconfig", "joint_raft",
    "reconfig_raft",
)

# lint engine geometry (DeviceBFS): small caps, real structure. The
# max_seen_cap of 1<<20 yields a TWO-size seen ladder (1<<18, 1<<20) so
# the signature pass proves closure over a non-trivial ladder without
# the donation pass paying for extra wave lowerings.
DEVICE_KW = dict(
    chunk=256,
    frontier_cap=1 << 10,
    seen_cap=1 << 12,
    journal_cap=1 << 12,
    max_seen_cap=1 << 20,
)

SHARDED_KW = dict(
    chunk=256,
    frontier_cap=1 << 10,
    seen_cap=1 << 12,
    max_seen_cap=1 << 18,
)

INVARIANTS = {
    "raft": ("NoLogDivergence",),
    "pull_raft": ("NoLogDivergence",),
    "kraft": ("NoLogDivergence",),
    "joint_raft": ("NoLogDivergence",),
    "reconfig_raft": ("NoLogDivergence",),
    "kraft_reconfig": ("NoLogDivergence",),
}


def family_module(name: str):
    mod, _, _ = FAMILY_PARAMS[name]
    return importlib.import_module(f"raft_tpu.models.{mod}")


def tiny_params(name: str):
    mod, cls, kw = FAMILY_PARAMS[name]
    return getattr(family_module(name), cls)(**kw)


def tiny_model(name: str):
    """The shared (memoized) tiny model for ``name`` — reuses the test
    suite's instance and its jitted kernels when already built."""
    return family_module(name).cached_model(tiny_params(name))


def fresh_tiny_model(name: str):
    """A NEVER-cached instance: mutation self-tests patch model-building
    hooks and must not poison the shared ``cached_model`` entry."""
    return type(tiny_model(name))(tiny_params(name))


def device_engine(name: str, model=None, **overrides):
    from ..checker.device_bfs import DeviceBFS

    kw = dict(DEVICE_KW)
    kw.update(overrides)
    return DeviceBFS(
        model if model is not None else tiny_model(name),
        invariants=INVARIANTS[name], symmetry=True, **kw,
    )


def sharded_engine(name: str, **overrides):
    import jax

    from ..parallel.sharded import ShardedBFS

    kw = dict(SHARDED_KW)
    kw.update(overrides)
    return ShardedBFS(
        tiny_model(name), invariants=INVARIANTS[name], symmetry=True,
        devices=jax.devices()[:1], **kw,
    )
