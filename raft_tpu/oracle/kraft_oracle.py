"""Independent pure-Python interpreter of pull-raft/KRaft.tla.

Differential-testing ground truth for the TPU lowering in models/kraft.py,
written directly against the TLA+ text (reference
``/root/reference/specifications/pull-raft/KRaft.tla``, 961 lines) — NOT
against the JAX kernels.

Key structural deltas vs. PullRaft (see SURVEY.md §2.1):
  - five server states plus IllegalState (``KRaft.tla:69,87``): Unattached
    and Voted precede the usual three; an explicit transition machine
    (``HasConsistentLeader:316``, ``MaybeTransition:351``,
    ``MaybeHandleCommonResponse:369``) governs receipt-driven changes;
  - fetch-based replication with a ``pendingFetch`` correlation register
    (``KRaft.tla:123``): the follower records the exact FetchRequest it
    sent and only a FetchResponse whose ``correlation`` field equals it is
    processable (``:749,774,794``);
  - three fetch-response shapes keyed by ``mresult`` (Ok / NotOk /
    Diverging, ``KRaft.tla:81``) plus error codes (``:84``);
  - diverging-epoch truncation via ``EndOffsetForEpoch`` (``:285-301``) and
    ``HighestCommonOffset`` (``:255-273``);
  - ``Reply`` refuses to duplicate a FetchResponse (``KRaft.tla:220-227``),
    the anti-infinite-empty-fetch rule;
  - ``RequestVoteRequest``/``BeginQuorumRequest`` are send-once, FetchRequest
    is unrestricted (``KRaft.tla:190-194``).

State dict format (shared with KRaftModel.decode/encode):
  currentEpoch, state, votedFor (int|None), leader (int|None),
  pendingFetch (None | record tuple), votesGranted (frozensets),
  endOffset (SxS), log, highWatermark, messages, acked, electionCtr,
  restartCtr.
"""

from __future__ import annotations

import itertools

# state encoding shared with models/kraft.py (QuorumState machine,
# KRaft.tla:33-56)
UNATTACHED, VOTED, FOLLOWER, CANDIDATE, LEADER, ILLEGAL = range(6)

# error codes (KRaft.tla:84)
NO_ERROR = None
FENCED = "FencedLeaderEpoch"
NOT_LEADER = "NotLeader"
UNKNOWN_LEADER = "UnknownLeader"

OK, NOT_OK, DIVERGING = "Ok", "NotOk", "Diverging"


def rec(**kw) -> tuple:
    return tuple(sorted(kw.items()))


def last_epoch(log) -> int:
    """LastEpoch(xlog) — KRaft.tla:165."""
    return log[-1][0] if log else 0


def compare_entries(offset1, epoch1, offset2, epoch2) -> int:
    """CompareEntries — KRaft.tla:247-251 (epoch takes precedence)."""
    if epoch1 > epoch2:
        return 1
    if epoch1 == epoch2 and offset1 > offset2:
        return 1
    if epoch1 == epoch2 and offset1 == offset2:
        return 0
    return -1


def end_offset_for_epoch(log, last_fetched_epoch) -> tuple[int, int]:
    """EndOffsetForEpoch(i, lastFetchedEpoch) — KRaft.tla:285-301: the
    highest offset whose entry epoch is <= lastFetchedEpoch, as
    (offset, epoch); (0, 0) when none."""
    best = 0
    for off in range(1, len(log) + 1):
        if log[off - 1][0] <= last_fetched_epoch:
            best = off
    if best == 0:
        return (0, 0)
    return (best, log[best - 1][0])


def highest_common_offset(log, end_offset: int, epoch: int) -> tuple[int, int]:
    """HighestCommonOffset(i, endOffsetForEpoch, epoch) — KRaft.tla:255-273:
    highest offset with CompareEntries(offset, log[offset].epoch,
    end_offset, epoch) <= 0; (0, 0) when none."""
    best = 0
    for off in range(1, len(log) + 1):
        if compare_entries(off, log[off - 1][0], end_offset, epoch) <= 0:
            best = off
    if best == 0:
        return (0, 0)
    return (best, log[best - 1][0])


class KRaftOracle:
    def __init__(
        self,
        n_servers: int,
        n_values: int,
        max_elections: int,
        max_restarts: int,
    ):
        self.S = n_servers
        self.V = n_values
        self.max_elections = max_elections
        self.max_restarts = max_restarts

    # ---------- state helpers ----------

    def init_state(self) -> dict:
        """Init — KRaft.tla:397-415."""
        S, V = self.S, self.V
        return {
            "currentEpoch": (1,) * S,
            "state": (UNATTACHED,) * S,
            "votedFor": (None,) * S,
            "leader": (None,) * S,
            "pendingFetch": (None,) * S,
            "votesGranted": (frozenset(),) * S,
            "endOffset": ((0,) * S,) * S,
            "log": ((),) * S,
            "highWatermark": (0,) * S,
            "messages": frozenset(),
            "acked": (None,) * V,
            "electionCtr": 0,
            "restartCtr": 0,
        }

    @staticmethod
    def _msgs(st) -> dict:
        return dict(st["messages"])

    @staticmethod
    def _with(st, **updates) -> dict:
        out = dict(st)
        out.update(updates)
        return out

    @staticmethod
    def _set(tup, i, val) -> tuple:
        return tup[:i] + (val,) + tup[i + 1 :]

    @classmethod
    def _set2(cls, mat, i, j, val) -> tuple:
        return cls._set(mat, i, cls._set(mat[i], j, val))

    # ---------- message-bag helpers (KRaft.tla:167-227) ----------

    @staticmethod
    def _send_no_restriction(msgs, m):
        """_SendNoRestriction — KRaft.tla:169-173."""
        out = dict(msgs)
        out[m] = out.get(m, 0) + 1
        return frozenset(out.items())

    @staticmethod
    def _send_once(msgs, m):
        """_SendOnce — KRaft.tla:178-180; None when m already in DOMAIN."""
        if m in msgs:
            return None
        out = dict(msgs)
        out[m] = 1
        return frozenset(out.items())

    @classmethod
    def _send(cls, msgs, m):
        """Send — KRaft.tla:190-194: RequestVoteRequest/BeginQuorumRequest
        are send-once, everything else unrestricted."""
        mtype = dict(m)["mtype"]
        if mtype in ("RequestVoteRequest", "BeginQuorumRequest"):
            return cls._send_once(msgs, m)
        return cls._send_no_restriction(msgs, m)

    @staticmethod
    def _send_multiple_once(msgs, ms):
        """SendMultipleOnce — KRaft.tla:199-201; None when any exists."""
        if any(m in msgs for m in ms):
            return None
        out = dict(msgs)
        for m in ms:
            out[m] = 1
        return frozenset(out.items())

    @staticmethod
    def _reply(msgs, response, request):
        """Reply — KRaft.tla:220-227: decrement request, add/increment the
        response; a FetchResponse may not be duplicated (anti-cycle rule).
        Returns None when disabled."""
        out = dict(msgs)
        if out.get(request, 0) < 1:
            return None
        if response in out and dict(response)["mtype"] == "FetchResponse":
            return None
        out[request] -= 1
        out[response] = out.get(response, 0) + 1
        return frozenset(out.items())

    @staticmethod
    def _discard(msgs, m):
        """Discard — KRaft.tla:210-213."""
        out = dict(msgs)
        assert out.get(m, 0) > 0
        out[m] -= 1
        return frozenset(out.items())

    def _receivable(self, st, m, mtype: str, equal_epoch: bool) -> bool:
        """ReceivableMessage — KRaft.tla:230-235."""
        d = dict(m)
        msgs = self._msgs(st)
        if msgs.get(m, 0) < 1 or d["mtype"] != mtype:
            return False
        if equal_epoch and d["mepoch"] != st["currentEpoch"][d["mdest"]]:
            return False
        return True

    def _domain(self, st):
        """DOMAIN messages, in a deterministic order."""
        return sorted((m for m, _c in st["messages"]), key=self._norm_rec)

    # ---------- transition machine (KRaft.tla:312-392) ----------

    def _has_consistent_leader(self, st, i, leader_id, epoch) -> bool:
        """HasConsistentLeader — KRaft.tla:316-327."""
        if leader_id == i:
            return st["state"][i] == LEADER
        return (
            epoch != st["currentEpoch"][i]
            or leader_id is None
            or st["leader"][i] is None
            or st["leader"][i] == leader_id
        )

    @staticmethod
    def _illegal():
        """SetIllegalState — KRaft.tla:329-330."""
        return {"state": ILLEGAL, "epoch": 0, "leader": None}

    def _no_transition(self, st, i):
        """NoTransition — KRaft.tla:332-333."""
        return {
            "state": st["state"][i],
            "epoch": st["currentEpoch"][i],
            "leader": st["leader"][i],
        }

    def _to_voted(self, st, i, epoch, state0):
        """TransitionToVoted — KRaft.tla:335-339."""
        if state0["epoch"] == epoch and state0["state"] != UNATTACHED:
            return self._illegal()
        return {"state": VOTED, "epoch": epoch, "leader": None}

    @staticmethod
    def _to_unattached(epoch):
        """TransitionToUnattached — KRaft.tla:341-342."""
        return {"state": UNATTACHED, "epoch": epoch, "leader": None}

    def _to_follower(self, st, i, leader_id, epoch):
        """TransitionToFollower — KRaft.tla:344-349."""
        if st["currentEpoch"][i] == epoch and st["state"][i] in (FOLLOWER, LEADER):
            return self._illegal()
        return {"state": FOLLOWER, "epoch": epoch, "leader": leader_id}

    def _maybe_transition(self, st, i, leader_id, epoch):
        """MaybeTransition — KRaft.tla:351-367."""
        if not self._has_consistent_leader(st, i, leader_id, epoch):
            return self._illegal()
        if epoch > st["currentEpoch"][i]:
            if leader_id is None:
                return self._to_unattached(epoch)
            return self._to_follower(st, i, leader_id, epoch)
        if leader_id is not None and st["leader"][i] is None:
            return self._to_follower(st, i, leader_id, epoch)
        return self._no_transition(st, i)

    def _maybe_handle_common_response(self, st, i, leader_id, epoch, errors):
        """MaybeHandleCommonResponse — KRaft.tla:369-392."""
        if epoch < st["currentEpoch"][i]:
            return self._no_transition(st, i) | {"handled": True}
        if epoch > st["currentEpoch"][i] or errors is not None:
            return self._maybe_transition(st, i, leader_id, epoch) | {"handled": True}
        if (
            epoch == st["currentEpoch"][i]
            and leader_id is not None
            and st["leader"][i] is None
        ):
            return {
                "state": FOLLOWER,
                "leader": leader_id,
                "epoch": st["currentEpoch"][i],
                "handled": True,
            }
        return self._no_transition(st, i) | {"handled": False}

    def _apply_transition(self, st, i, new, clear_pending=False, **extra):
        """state/leader/currentEpoch := transition record fields."""
        upd = dict(
            state=self._set(st["state"], i, new["state"]),
            leader=self._set(st["leader"], i, new["leader"]),
            currentEpoch=self._set(st["currentEpoch"], i, new["epoch"]),
            **extra,
        )
        if clear_pending:
            upd["pendingFetch"] = self._set(st["pendingFetch"], i, None)
        return self._with(st, **upd)

    # ---------- fetch-position helpers (KRaft.tla:276-310) ----------

    def _truncate_log(self, st, i, m) -> tuple:
        """TruncateLog — KRaft.tla:276-282."""
        d = dict(m)
        hco, _epoch = highest_common_offset(
            st["log"][i], d["mdivergingEndOffset"], d["mdivergingEpoch"]
        )
        return st["log"][i][:hco]

    def _valid_fetch_position(self, st, i, m) -> bool:
        """ValidFetchPosition — KRaft.tla:305-310."""
        d = dict(m)
        if d["mfetchOffset"] == 0 and d["mlastFetchedEpoch"] == 0:
            return True
        off, ep = end_offset_for_epoch(st["log"][i], d["mlastFetchedEpoch"])
        return d["mfetchOffset"] <= off and d["mlastFetchedEpoch"] == ep

    # ---------- actions (Next order, KRaft.tla:823-840) ----------

    def successors(self, st) -> list[tuple[str, dict]]:
        out = []
        S, V = self.S, self.V
        for i in range(S):
            s2 = self.restart(st, i)
            if s2 is not None:
                out.append((f"Restart({i})", s2))
        for i in range(S):
            s2 = self.request_vote(st, i)
            if s2 is not None:
                out.append((f"RequestVote({i})", s2))
        for m in self._domain(st):
            s2 = self.handle_request_vote_request(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteRequest", s2))
        for m in self._domain(st):
            s2 = self.handle_request_vote_response(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteResponse", s2))
        for i in range(S):
            s2 = self.become_leader(st, i)
            if s2 is not None:
                out.append((f"BecomeLeader({i})", s2))
        for i in range(S):
            for v in range(V):
                s2 = self.client_request(st, i, v)
                if s2 is not None:
                    out.append((f"ClientRequest({i},{v})", s2))
        for m in self._domain(st):
            s2 = self.reject_fetch_request(st, m)
            if s2 is not None:
                out.append(("RejectFetchRequest", s2))
        for m in self._domain(st):
            s2 = self.diverging_fetch_request(st, m)
            if s2 is not None:
                out.append(("DivergingFetchRequest", s2))
        for m in self._domain(st):
            s2 = self.accept_fetch_request(st, m)
            if s2 is not None:
                out.append(("AcceptFetchRequest", s2))
        for m in self._domain(st):
            s2 = self.handle_begin_quorum_request(st, m)
            if s2 is not None:
                out.append(("HandleBeginQuorumRequest", s2))
        for i in range(S):
            for j in range(S):
                if i != j:
                    s2 = self.send_fetch_request(st, i, j)
                    if s2 is not None:
                        out.append((f"SendFetchRequest({i},{j})", s2))
        for m in self._domain(st):
            s2 = self.handle_success_fetch_response(st, m)
            if s2 is not None:
                out.append(("HandleSuccessFetchResponse", s2))
        for m in self._domain(st):
            s2 = self.handle_diverging_fetch_response(st, m)
            if s2 is not None:
                out.append(("HandleDivergingFetchResponse", s2))
        for m in self._domain(st):
            s2 = self.handle_error_fetch_response(st, m)
            if s2 is not None:
                out.append(("HandleErrorFetchResponse", s2))
        return out

    def restart(self, st, i):
        """Restart(i) — KRaft.tla:423-432: keeps currentEpoch, votedFor and
        log; loses leader belief, votes, endOffset, hwm, pendingFetch."""
        if st["restartCtr"] >= self.max_restarts:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, FOLLOWER),
            leader=self._set(st["leader"], i, None),
            votesGranted=self._set(st["votesGranted"], i, frozenset()),
            endOffset=self._set(st["endOffset"], i, (0,) * self.S),
            highWatermark=self._set(st["highWatermark"], i, 0),
            pendingFetch=self._set(st["pendingFetch"], i, None),
            restartCtr=st["restartCtr"] + 1,
        )

    def request_vote(self, st, i):
        """RequestVote(i) — KRaft.tla:439-456 (fused Timeout+RequestVote)."""
        if st["electionCtr"] >= self.max_elections:
            return None
        if st["state"][i] not in (FOLLOWER, CANDIDATE, UNATTACHED):
            return None
        new_epoch = st["currentEpoch"][i] + 1
        reqs = {
            rec(
                mtype="RequestVoteRequest",
                mepoch=new_epoch,
                mlastLogEpoch=last_epoch(st["log"][i]),
                mlastLogOffset=len(st["log"][i]),
                msource=i,
                mdest=j,
            )
            for j in range(self.S)
            if j != i
        }
        msgs = self._send_multiple_once(self._msgs(st), reqs)
        if msgs is None:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, CANDIDATE),
            currentEpoch=self._set(st["currentEpoch"], i, new_epoch),
            leader=self._set(st["leader"], i, None),
            votedFor=self._set(st["votedFor"], i, i),
            votesGranted=self._set(st["votesGranted"], i, frozenset({i})),
            pendingFetch=self._set(st["pendingFetch"], i, None),
            electionCtr=st["electionCtr"] + 1,
            messages=msgs,
        )

    def handle_request_vote_request(self, st, m):
        """HandleRequestVoteRequest — KRaft.tla:464-513."""
        if not self._receivable(st, m, "RequestVoteRequest", equal_epoch=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        error = FENCED if d["mepoch"] < st["currentEpoch"][i] else None
        if error is not None:
            resp = rec(
                mtype="RequestVoteResponse",
                mepoch=st["currentEpoch"][i],
                mleader=st["leader"][i],
                mvoteGranted=False,
                merror=error,
                msource=i,
                mdest=j,
            )
            msgs = self._reply(self._msgs(st), resp, m)
            if msgs is None:
                return None
            return self._with(st, messages=msgs)
        state0 = (
            self._to_unattached(d["mepoch"])
            if d["mepoch"] > st["currentEpoch"][i]
            else self._no_transition(st, i)
        )
        log_ok = (
            compare_entries(
                d["mlastLogOffset"],
                d["mlastLogEpoch"],
                len(st["log"][i]),
                last_epoch(st["log"][i]),
            )
            >= 0
        )
        grant = (
            state0["state"] == UNATTACHED
            or (state0["state"] == VOTED and st["votedFor"][i] == j)
        ) and log_ok
        final = (
            self._to_voted(st, i, d["mepoch"], state0)
            if grant and state0["state"] == UNATTACHED
            else state0
        )
        resp = rec(
            mtype="RequestVoteResponse",
            mepoch=d["mepoch"],
            mleader=final["leader"],
            mvoteGranted=grant,
            merror=None,
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        extra = {}
        if grant:
            extra["votedFor"] = self._set(st["votedFor"], i, j)
        # IF state # state' THEN reset pendingFetch (KRaft.tla:495-497)
        clear = final["state"] != st["state"][i]
        return self._apply_transition(
            st, i, final, clear_pending=clear, messages=msgs, **extra
        )

    def handle_request_vote_response(self, st, m):
        """HandleRequestVoteResponse — KRaft.tla:519-541."""
        if not self._receivable(st, m, "RequestVoteResponse", equal_epoch=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        new = self._maybe_handle_common_response(
            st, i, d["mleader"], d["mepoch"], d["merror"]
        )
        msgs = self._discard(self._msgs(st), m)
        if new["handled"]:
            return self._apply_transition(st, i, new, messages=msgs)
        if st["state"][i] != CANDIDATE:
            return None
        vg = st["votesGranted"][i] | {j} if d["mvoteGranted"] else st["votesGranted"][i]
        return self._with(
            st, votesGranted=self._set(st["votesGranted"], i, vg), messages=msgs
        )

    def become_leader(self, st, i):
        """BecomeLeader(i) — KRaft.tla:546-558."""
        if st["state"][i] != CANDIDATE:
            return None
        if 2 * len(st["votesGranted"][i]) <= self.S:
            return None
        reqs = {
            rec(
                mtype="BeginQuorumRequest",
                mepoch=st["currentEpoch"][i],
                msource=i,
                mdest=j,
            )
            for j in range(self.S)
            if j != i
        }
        msgs = self._send_multiple_once(self._msgs(st), reqs)
        if msgs is None:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, LEADER),
            leader=self._set(st["leader"], i, i),
            endOffset=self._set(st["endOffset"], i, (0,) * self.S),
            messages=msgs,
        )

    def handle_begin_quorum_request(self, st, m):
        """HandleBeginQuorumRequest — KRaft.tla:563-590."""
        if not self._receivable(st, m, "BeginQuorumRequest", equal_epoch=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        error = FENCED if d["mepoch"] < st["currentEpoch"][i] else None
        if error is None:
            new = self._maybe_transition(st, i, j, d["mepoch"])
            resp = rec(
                mtype="BeginQuorumResponse",
                mepoch=d["mepoch"],
                msource=i,
                mdest=j,
                merror=None,
            )
            msgs = self._reply(self._msgs(st), resp, m)
            if msgs is None:
                return None
            return self._apply_transition(
                st, i, new, clear_pending=True, messages=msgs
            )
        resp = rec(
            mtype="BeginQuorumResponse",
            mepoch=st["currentEpoch"][i],
            msource=i,
            mdest=j,
            merror=error,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def client_request(self, st, i, v):
        """ClientRequest(i, v) — KRaft.tla:594-603."""
        if st["state"][i] != LEADER or st["acked"][v] is not None:
            return None
        entry = (st["currentEpoch"][i], v)
        return self._with(
            st,
            log=self._set(st["log"], i, st["log"][i] + (entry,)),
            acked=self._set(st["acked"], v, False),
        )

    def send_fetch_request(self, st, i, j):
        """SendFetchRequest(i, j) — KRaft.tla:607-624."""
        if st["state"][i] != FOLLOWER:
            return None
        if st["leader"][i] != j or st["pendingFetch"][i] is not None:
            return None
        fetch = rec(
            mtype="FetchRequest",
            mepoch=st["currentEpoch"][i],
            mfetchOffset=len(st["log"][i]),
            mlastFetchedEpoch=last_epoch(st["log"][i]),
            msource=i,
            mdest=j,
        )
        msgs = self._send(self._msgs(st), fetch)
        if msgs is None:
            return None
        return self._with(
            st,
            pendingFetch=self._set(st["pendingFetch"], i, fetch),
            messages=msgs,
        )

    def reject_fetch_request(self, st, m):
        """RejectFetchRequest — KRaft.tla:631-651."""
        if not self._receivable(st, m, "FetchRequest", equal_epoch=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER:
            error = NOT_LEADER
        elif d["mepoch"] < st["currentEpoch"][i]:
            error = FENCED
        elif d["mepoch"] > st["currentEpoch"][i]:
            error = UNKNOWN_LEADER
        else:
            return None
        resp = rec(
            mtype="FetchResponse",
            mresult=NOT_OK,
            merror=error,
            mleader=st["leader"][i],
            mepoch=st["currentEpoch"][i],
            mhwm=st["highWatermark"][i],
            msource=i,
            mdest=j,
            correlation=m,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def diverging_fetch_request(self, st, m):
        """DivergingFetchRequest — KRaft.tla:658-679."""
        if not self._receivable(st, m, "FetchRequest", equal_epoch=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER or self._valid_fetch_position(st, i, m):
            return None
        off, ep = end_offset_for_epoch(st["log"][i], d["mlastFetchedEpoch"])
        resp = rec(
            mtype="FetchResponse",
            mepoch=st["currentEpoch"][i],
            mresult=DIVERGING,
            merror=None,
            mdivergingEpoch=ep,
            mdivergingEndOffset=off,
            mleader=st["leader"][i],
            mhwm=st["highWatermark"][i],
            msource=i,
            mdest=j,
            correlation=m,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def _new_highwatermark(self, st, i, new_end_offset) -> int:
        """NewHighwaterMark — KRaft.tla:689-701."""
        best = 0
        for off in range(1, len(st["log"][i]) + 1):
            agree = {i} | {k for k in range(self.S) if new_end_offset[k] >= off}
            if 2 * len(agree) > self.S:
                best = off
        if best > 0 and st["log"][i][best - 1][0] == st["currentEpoch"][i]:
            return best
        return st["highWatermark"][i]

    def accept_fetch_request(self, st, m):
        """AcceptFetchRequest — KRaft.tla:703-736."""
        if not self._receivable(st, m, "FetchRequest", equal_epoch=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER or not self._valid_fetch_position(st, i, m):
            return None
        offset = d["mfetchOffset"] + 1
        entries = (
            () if offset > len(st["log"][i]) else (st["log"][i][offset - 1],)
        )
        new_end = self._set(st["endOffset"][i], j, d["mfetchOffset"])
        new_hwm = self._new_highwatermark(st, i, new_end)
        committed_vals = {
            st["log"][i][ind - 1][1]
            for ind in range(st["highWatermark"][i] + 1, new_hwm + 1)
        }
        acked = tuple(
            (v in committed_vals) if st["acked"][v] is False else st["acked"][v]
            for v in range(self.V)
        )
        resp = rec(
            mtype="FetchResponse",
            mepoch=st["currentEpoch"][i],
            mleader=st["leader"][i],
            mresult=OK,
            merror=None,
            mentries=entries,
            mhwm=min(new_hwm, offset),
            msource=i,
            mdest=j,
            correlation=m,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(
            st,
            endOffset=self._set(st["endOffset"], i, new_end),
            highWatermark=self._set(st["highWatermark"], i, new_hwm),
            acked=acked,
            messages=msgs,
        )

    def handle_success_fetch_response(self, st, m):
        """HandleSuccessFetchResponse — KRaft.tla:742-757."""
        if not self._receivable(st, m, "FetchResponse", equal_epoch=False):
            return None
        d = dict(m)
        i = d["mdest"]
        new = self._maybe_handle_common_response(
            st, i, d["mleader"], d["mepoch"], d["merror"]
        )
        if new["handled"] or st["pendingFetch"][i] != d["correlation"]:
            return None
        if d["mresult"] != OK:
            return None
        log_i = st["log"][i]
        if len(d["mentries"]) > 0:
            log_i = log_i + (d["mentries"][0],)
        return self._with(
            st,
            highWatermark=self._set(st["highWatermark"], i, d["mhwm"]),
            log=self._set(st["log"], i, log_i),
            pendingFetch=self._set(st["pendingFetch"], i, None),
            messages=self._discard(self._msgs(st), m),
        )

    def handle_diverging_fetch_response(self, st, m):
        """HandleDivergingFetchResponse — KRaft.tla:766-780."""
        if not self._receivable(st, m, "FetchResponse", equal_epoch=False):
            return None
        d = dict(m)
        i = d["mdest"]
        new = self._maybe_handle_common_response(
            st, i, d["mleader"], d["mepoch"], d["merror"]
        )
        if new["handled"] or st["pendingFetch"][i] != d["correlation"]:
            return None
        if d["mresult"] != DIVERGING:
            return None
        return self._with(
            st,
            log=self._set(st["log"], i, self._truncate_log(st, i, m)),
            pendingFetch=self._set(st["pendingFetch"], i, None),
            messages=self._discard(self._msgs(st), m),
        )

    def handle_error_fetch_response(self, st, m):
        """HandleErrorFetchResponse — KRaft.tla:786-801."""
        if not self._receivable(st, m, "FetchResponse", equal_epoch=False):
            return None
        d = dict(m)
        i = d["mdest"]
        new = self._maybe_handle_common_response(
            st, i, d["mleader"], d["mepoch"], d["merror"]
        )
        if not new["handled"] or st["pendingFetch"][i] != d["correlation"]:
            return None
        return self._apply_transition(
            st,
            i,
            new,
            clear_pending=True,
            messages=self._discard(self._msgs(st), m),
        )

    # ---------- VIEW + SYMMETRY ----------

    @staticmethod
    def _norm_rec(m) -> tuple:
        """Make record values totally orderable across None / bool / int /
        str / nested record (correlation) / entry tuples via type tags."""

        def norm_val(v):
            if v is None:
                return (0, 0)
            if isinstance(v, bool):
                return (1, int(v))
            if isinstance(v, int):
                return (2, v)
            if isinstance(v, str):
                return (3, v)
            if isinstance(v, tuple) and v and isinstance(v[0], tuple) and len(
                v[0]
            ) == 2 and isinstance(v[0][0], str):
                return (4, KRaftOracle._norm_rec(v))  # nested record
            return (5, v)

        return tuple((k, norm_val(v)) for k, v in m)

    def _ser_msgs(self, msgs) -> tuple:
        return tuple(sorted((self._norm_rec(m), c) for m, c in msgs))

    def serialize_view(self, st) -> tuple:
        """view — KRaft.tla:154: everything except electionCtr/restartCtr
        (acked IS included)."""
        ack = {None: -1, False: 0, True: 1}
        return (
            st["currentEpoch"],
            st["state"],
            tuple(-1 if v is None else v for v in st["votedFor"]),
            tuple(-1 if v is None else v for v in st["leader"]),
            tuple(
                () if pf is None else self._norm_rec(pf)
                for pf in st["pendingFetch"]
            ),
            tuple(tuple(sorted(vs)) for vs in st["votesGranted"]),
            st["endOffset"],
            st["log"],
            st["highWatermark"],
            self._ser_msgs(st["messages"]),
            tuple(ack[a] for a in st["acked"]),
        )

    def serialize_full(self, st) -> tuple:
        return self.serialize_view(st) + (st["electionCtr"], st["restartCtr"])

    def permute(self, st, sigma) -> dict:
        """Apply a server permutation (old -> new index)."""
        S = self.S
        inv = [0] * S
        for old, new in enumerate(sigma):
            inv[new] = old

        def prow(t):
            return tuple(t[inv[k]] for k in range(S))

        def pmsg(m):
            d = dict(m)
            d["msource"] = sigma[d["msource"]]
            d["mdest"] = sigma[d["mdest"]]
            if d.get("mleader") is not None:
                d["mleader"] = sigma[d["mleader"]]
            if "correlation" in d:
                d["correlation"] = pmsg(d["correlation"])
            return rec(**d)

        return self._with(
            st,
            currentEpoch=prow(st["currentEpoch"]),
            state=prow(st["state"]),
            votedFor=tuple(
                None if v is None else sigma[v] for v in prow(st["votedFor"])
            ),
            leader=tuple(None if v is None else sigma[v] for v in prow(st["leader"])),
            pendingFetch=tuple(
                None if pf is None else pmsg(pf) for pf in prow(st["pendingFetch"])
            ),
            votesGranted=tuple(
                frozenset(sigma[j] for j in vs) for vs in prow(st["votesGranted"])
            ),
            endOffset=tuple(prow(row) for row in prow(st["endOffset"])),
            log=prow(st["log"]),
            highWatermark=prow(st["highWatermark"]),
            messages=frozenset((pmsg(m), c) for m, c in st["messages"]),
        )

    def canon(self, st, symmetry: bool = True) -> tuple:
        if not symmetry:
            return self.serialize_view(st)
        return min(
            self.serialize_view(self.permute(st, list(sigma)))
            for sigma in itertools.permutations(range(self.S))
        )

    # ---------- invariants (KRaft.tla:884-957) ----------

    def no_illegal_state(self, st) -> bool:
        """NoIllegalState — KRaft.tla:887-889."""
        return all(s != ILLEGAL for s in st["state"])

    def no_log_divergence(self, st) -> bool:
        """NoLogDivergence — KRaft.tla:894-907 (common prefix up to the
        MINIMUM highWatermark, not commitIndex)."""
        for s1 in range(self.S):
            for s2 in range(self.S):
                if s1 == s2:
                    continue
                hwm = min(st["highWatermark"][s1], st["highWatermark"][s2])
                for off in range(1, hwm + 1):
                    if st["log"][s1][off - 1] != st["log"][s2][off - 1]:
                        return False
        return True

    def never_two_leaders_in_same_epoch(self, st) -> bool:
        """NeverTwoLeadersInSameEpoch — KRaft.tla:916-921 (conflicting
        leader BELIEFS at equal epochs)."""
        for i in range(self.S):
            for j in range(self.S):
                if (
                    st["leader"][i] is not None
                    and st["leader"][j] is not None
                    and st["leader"][i] != st["leader"][j]
                    and st["currentEpoch"][i] == st["currentEpoch"][j]
                ):
                    return False
        return True

    def leader_has_all_acked_values(self, st) -> bool:
        """LeaderHasAllAckedValues — KRaft.tla:925-941."""
        for v in range(self.V):
            if st["acked"][v] is not True:
                continue
            for i in range(self.S):
                if st["state"][i] != LEADER:
                    continue
                if any(
                    st["currentEpoch"][l] > st["currentEpoch"][i]
                    for l in range(self.S)
                    if l != i
                ):
                    continue
                if not any(e[1] == v for e in st["log"][i]):
                    return False
        return True

    def committed_entries_reach_majority(self, st) -> bool:
        """CommittedEntriesReachMajority — KRaft.tla:946-957."""
        leaders = [
            i
            for i in range(self.S)
            if st["state"][i] == LEADER and st["highWatermark"][i] > 0
        ]
        if not leaders:
            return True
        need = self.S // 2 + 1
        for i in leaders:
            hwm = st["highWatermark"][i]
            entry = st["log"][i][hwm - 1]
            n = sum(
                1
                for j in range(self.S)
                if len(st["log"][j]) >= hwm and st["log"][j][hwm - 1] == entry
            )
            if n >= need:
                return True
        return False

    INVARIANTS = {
        "NoIllegalState": no_illegal_state,
        "NoLogDivergence": no_log_divergence,
        "NeverTwoLeadersInSameEpoch": never_two_leaders_in_same_epoch,
        "LeaderHasAllAckedValues": leader_has_all_acked_values,
        "CommittedEntriesReachMajority": committed_entries_reach_majority,
        "TestInv": lambda self, st: True,
    }

    # ---------- BFS ----------

    def bfs(
        self,
        invariants: tuple[str, ...] = (
            "LeaderHasAllAckedValues",
            "NoLogDivergence",
            "NeverTwoLeadersInSameEpoch",
            "NoIllegalState",
        ),
        symmetry: bool = True,
        max_depth: int | None = None,
        max_states: int | None = None,
        time_budget_s: float | None = None,
    ) -> dict:
        import time

        t0 = time.perf_counter()
        init = self.init_state()
        seen = {self.canon(init, symmetry)}
        frontier = [init]
        total = 1
        distinct = 1
        depth_counts = [1]
        violation = None
        depth = 0
        while frontier and violation is None:
            if max_depth is not None and depth >= max_depth:
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                break
            next_frontier = []
            for st in frontier:
                for _label, s2 in self.successors(st):
                    total += 1
                    key = self.canon(s2, symmetry)
                    if key in seen:
                        continue
                    seen.add(key)
                    distinct += 1
                    for inv in invariants:
                        if not self.INVARIANTS[inv](self, s2):
                            violation = {
                                "invariant": inv,
                                "state": s2,
                                "depth": depth + 1,
                            }
                            break
                    next_frontier.append(s2)
                    if violation or (max_states and distinct >= max_states):
                        break
                if violation or (max_states and distinct >= max_states):
                    break
                if (
                    time_budget_s is not None
                    and (total & 0x3FF) < 8
                    and time.perf_counter() - t0 > time_budget_s
                ):
                    break
            frontier = next_frontier
            if frontier:
                depth_counts.append(len(frontier))
            depth += 1
        return {
            "distinct": distinct,
            "total": total,
            "depth_counts": depth_counts,
            "violation": violation,
        }
