"""Independent pure-Python interpreter of
standard-raft/RaftWithReconfigJointConsensus.tla.

Differential-testing ground truth for the TPU lowering in
models/joint_raft.py, written directly against the TLA+ text (reference
``/root/reference/specifications/standard-raft/
RaftWithReconfigJointConsensus.tla``, 1,145 lines).

Key structural deltas vs. the add/remove variant (see SURVEY.md §2.1):
  - two-phase joint consensus: ``OldNewConfigCommand`` carries
    (id, old, new, members=old ∪ added) and flips the config into
    jointConsensus mode (``ConfigFor:279-290``); once committed, the
    leader appends the matching ``NewConfigCommand``
    (``CommittedOldNewWithoutNew:232-242``, ``AppendNewConfigToLog:861``);
  - DUAL quorums while joint: ``BecomeLeader:511-528`` needs majorities of
    both ``old`` and ``new``; ``AdvanceCommitIndex:613-653`` agrees in
    both sets;
  - the reconfiguration shape is constrained by ``ReconfigType:79-80``
    (1=any, 2=one-for-one swap, 3=add-only, 4=remove-only,
    ``IsValidReconfiguration:813-825``);
  - ``MaxOneReconfigurationAtATime:1080-1101`` is an adjacency rule over
    ALL servers' logs (same-type config commands must have the opposite
    type strictly between them);
  - ``ResetWithSameIdentity:391`` exists but is commented OUT of
    ``Next:988`` — it is not a successor;
  - ``Init:341-354`` seeds a ``NewConfigCommand`` first entry (not an
    InitClusterCommand).

Log entries are (command, term, value) with value:
  AppendCommand       -> int v
  OldNewConfigCommand -> (id, frozenset old, frozenset new, frozenset members)
  NewConfigCommand    -> (id, frozenset members)

Config tuples: (id, joint: bool, members, old, new, committed); old/new are
empty frozensets when not joint (absent record fields encode as empty).
"""

from __future__ import annotations

from .config_oracle_base import ConfigOracleBase, last_term, rec

import itertools

FOLLOWER, CANDIDATE, LEADER, NOTMEMBER = range(4)

APPEND_CMD = "AppendCommand"
OLDNEW_CMD = "OldNewConfigCommand"
NEW_CMD = "NewConfigCommand"
CONFIG_CMDS = (OLDNEW_CMD, NEW_CMD)

OK, STALE_TERM, ENTRY_MISMATCH, NEED_SNAPSHOT = (
    "Ok",
    "StaleTerm",
    "EntryMismatch",
    "NeedSnapshot",
)

PENDING_SNAP_REQUEST = -1  # :293
PENDING_SNAP_RESPONSE = -2  # :294

EMPTY_FS = frozenset()
NO_CONFIG = (0, False, EMPTY_FS, EMPTY_FS, EMPTY_FS, False)  # :267-271






def is_config_command(entry) -> bool:
    """IsConfigCommand — :226-228."""
    return entry[0] in CONFIG_CMDS


def most_recent_reconfig_entry(log) -> tuple[int, tuple]:
    """MostRecentReconfigEntry — :251-257."""
    best = 0
    for idx in range(1, len(log) + 1):
        if is_config_command(log[idx - 1]):
            best = idx
    assert best > 0, "log has no config command"
    return best, log[best - 1]


def config_for(index: int, entry: tuple, ci: int) -> tuple:
    """ConfigFor — :279-290."""
    cmd, _term, val = entry
    if cmd == OLDNEW_CMD:
        cfg_id, old, new, members = val
        return (cfg_id, True, members, old, new, ci >= index)
    cfg_id, members = val
    return (cfg_id, False, members, EMPTY_FS, EMPTY_FS, ci >= index)


class JointRaftOracle(ConfigOracleBase):
    def __init__(
        self,
        n_servers: int,
        n_values: int,
        init_cluster_size: int,
        max_elections: int,
        max_restarts: int,
        max_reconfigs: int,
        max_values_per_term: int,
        reconfig_type: int,
    ):
        self.S = n_servers
        self.V = n_values
        self.init_cluster_size = init_cluster_size
        self.max_elections = max_elections
        self.max_restarts = max_restarts
        self.max_reconfigs = max_reconfigs
        self.max_values_per_term = max_values_per_term
        self.reconfig_type = reconfig_type
        self.max_term = 1 + max_elections

    MEMBERS_IDX = 2  # member-set slot of the config tuple
    _config_for = staticmethod(config_for)
    _mrre = staticmethod(most_recent_reconfig_entry)

    # ---------- state helpers ----------

    def init_state(self) -> dict:
        """Init — :341-354: pre-installed cluster; the seed entry is a
        NewConfigCommand. CHOOSE realized as lowest indices."""
        S, V = self.S, self.V
        members = frozenset(range(self.init_cluster_size))
        leader = 0
        first = (NEW_CMD, 1, (1, members))
        return {
            "config": tuple(
                (1, False, members, EMPTY_FS, EMPTY_FS, True)
                if i in members
                else NO_CONFIG
                for i in range(S)
            ),
            "currentTerm": tuple(1 if i in members else 0 for i in range(S)),
            "state": tuple(
                LEADER if i == leader else FOLLOWER if i in members else NOTMEMBER
                for i in range(S)
            ),
            "votedFor": (None,) * S,
            "votesGranted": (frozenset(),) * S,
            "nextIndex": tuple(
                tuple(2 if (i == leader and j in members) else 1 for j in range(S))
                for i in range(S)
            ),
            "matchIndex": tuple(
                tuple(1 if (i == leader and j in members) else 0 for j in range(S))
                for i in range(S)
            ),
            "pendingResponse": ((False,) * S,) * S,
            "log": tuple((first,) if i in members else () for i in range(S)),
            "commitIndex": tuple(1 if i in members else 0 for i in range(S)),
            "messages": frozenset(),
            "acked": (None,) * V,
            "electionCtr": 0,
            "restartCtr": 0,
            "reconfigCtr": 0,
            "valueCtr": (0,) * self.max_term,
        }

    # ---------- message-bag helpers (:160-208) ----------

    @classmethod
    def _send(cls, msgs, m):
        """Send — :177-181: empty AppendEntriesRequest is send-once."""
        d = dict(m)
        if d["mtype"] == "AppendEntriesRequest" and d["mentries"] == ():
            return cls._send_once(msgs, m)
        return cls._send_no_restriction(msgs, m)

    @staticmethod
    def _reply(msgs, response, request):
        out = dict(msgs)
        if out.get(request, 0) < 1:
            return None
        out[request] -= 1
        out[response] = out.get(response, 0) + 1
        return frozenset(out.items())

    def _has_pending_config(self, st, i) -> bool:
        """HasPendingConfigCommand — :246-248."""
        return st["config"][i][5] is False or st["config"][i][1] is True

    def _quorum(self, subset, of) -> bool:
        return subset <= of and 2 * len(subset) > len(of)

    def _is_valid_reconfiguration(self, add, remove) -> bool:
        """IsValidReconfiguration — :813-825."""
        if self.reconfig_type == 2:
            return len(add) == 1 and len(remove) == 1
        if self.reconfig_type == 3:
            return len(add) > 0 and len(remove) == 0
        if self.reconfig_type == 4:
            return len(add) == 0 and len(remove) > 0
        return bool(add) or bool(remove)

    # ---------- actions (Next order, :966-988) ----------

    counter_keys = ("reconfigCtr",)

    def _config_successors(self, st) -> list:
        out = []
        for i in range(self.S):
            for add, remove in self._reconfig_shapes():
                s2 = self.append_old_new_config(st, i, add, remove)
                if s2 is not None:
                    out.append(
                        (
                            f"AppendOldNewConfigToLog({i},+{sorted(add)},-{sorted(remove)})",
                            s2,
                        )
                    )
        for i in range(self.S):
            s2 = self.append_new_config(st, i)
            if s2 is not None:
                out.append((f"AppendNewConfigToLog({i})", s2))
        return out

    # (no _tail_successors: ResetWithSameIdentity is commented out of
    # this spec's Next, :988)

    def _reconfig_shapes(self):
        """All (addMembers, removeMembers) subset pairs admitted by
        IsValidReconfiguration (:813-825), in a deterministic order."""
        servers = range(self.S)
        subsets = []
        for r in range(self.S + 1):
            subsets += [frozenset(c) for c in itertools.combinations(servers, r)]
        for add in subsets:
            for remove in subsets:
                if self._is_valid_reconfiguration(add, remove):
                    yield add, remove

    def become_leader(self, st, i):
        """BecomeLeader(i) — :511-528: dual quorums while joint."""
        if st["state"][i] != CANDIDATE:
            return None
        _id, joint, members, old, new, _committed = st["config"][i]
        vg = st["votesGranted"][i]
        if joint:
            # VotesGrantedInSet (:508-509) intersects before the quorum test
            if not (
                self._quorum(vg & old, old) and self._quorum(vg & new, new)
            ):
                return None
        else:
            if not self._quorum(vg, members):
                return None
        return self._with(
            st,
            state=self._set(st["state"], i, LEADER),
            nextIndex=self._set(
                st["nextIndex"], i, (len(st["log"][i]) + 1,) * self.S
            ),
            matchIndex=self._set(st["matchIndex"], i, (0,) * self.S),
            pendingResponse=self._set(st["pendingResponse"], i, (False,) * self.S),
        )

    _mrre = staticmethod(most_recent_reconfig_entry)
    _config_for = staticmethod(config_for)

    def _commit_agree_ok(self, st, i, idx) -> bool:
        """Dual-quorum agreement while joint (:626-629)."""
        _id, joint, members, old, new, _committed = st["config"][i]

        def agree(member_set):
            a = {k for k in member_set if st["matchIndex"][i][k] >= idx}
            if i in member_set:
                a |= {i}
            return a

        if joint:
            return self._quorum(agree(old), old) and self._quorum(
                agree(new), new
            )
        return self._quorum(agree(members), members)

    def _committed_removal(self, log_i, idx, i) -> bool:
        """IsRemovedFromCluster (:606-611): NewConfigCommand without i."""
        return log_i[idx - 1][0] == NEW_CMD and i not in log_i[idx - 1][2][1]

    def append_old_new_config(self, st, i, add, remove):
        """AppendOldNewConfigToLog — :827-856."""
        if st["state"][i] != LEADER:
            return None
        if st["reconfigCtr"] >= self.max_reconfigs:
            return None
        if self._has_pending_config(st, i):
            return None
        members = st["config"][i][2]
        if add & members != EMPTY_FS:
            return None
        if remove & members != remove:
            return None
        old = members
        new = (members - remove) | add
        joint_members = members | add
        entry = (
            OLDNEW_CMD,
            st["currentTerm"][i],
            (st["reconfigCtr"] + 1, old, new, joint_members),
        )
        new_log = st["log"][i] + (entry,)
        return self._with(
            st,
            log=self._set(st["log"], i, new_log),
            config=self._set(
                st["config"],
                i,
                config_for(len(new_log), entry, st["commitIndex"][i]),
            ),
            reconfigCtr=st["reconfigCtr"] + 1,
            nextIndex=self._set(
                st["nextIndex"],
                i,
                tuple(
                    PENDING_SNAP_REQUEST
                    if (s in new and s not in old)
                    else st["nextIndex"][i][s]
                    for s in range(self.S)
                ),
            ),
        )

    def append_new_config(self, st, i):
        """AppendNewConfigToLog — :861-876 (the qualifying OldNew index,
        when it exists, is unique: no later OldNew and no later New)."""
        if st["state"][i] != LEADER:
            return None
        log_i = st["log"][i]
        target = None
        for idx in range(1, len(log_i) + 1):
            # CommittedOldNewWithoutNew (:232-242)
            if log_i[idx - 1][0] != OLDNEW_CMD:
                continue
            if st["commitIndex"][i] < idx:
                continue
            if any(
                log_i[k - 1][0] == OLDNEW_CMD and k > idx
                for k in range(1, len(log_i) + 1)
            ):
                continue
            if any(
                log_i[k - 1][0] == NEW_CMD and k > idx
                for k in range(1, len(log_i) + 1)
            ):
                continue
            target = idx
            break
        if target is None:
            return None
        oldnew = log_i[target - 1]
        entry = (NEW_CMD, st["currentTerm"][i], (oldnew[2][0], oldnew[2][2]))
        new_log = log_i + (entry,)
        return self._with(
            st,
            log=self._set(st["log"], i, new_log),
            config=self._set(
                st["config"],
                i,
                config_for(len(new_log), entry, st["commitIndex"][i]),
            ),
        )

    def _ser_entry(self, e) -> tuple:
        cmd, term, val = e
        if cmd == APPEND_CMD:
            return (cmd, term, (val,))
        if cmd == NEW_CMD:
            return (cmd, term, (val[0], tuple(sorted(val[1]))))
        return (
            cmd,
            term,
            (
                val[0],
                tuple(sorted(val[1])),
                tuple(sorted(val[2])),
                tuple(sorted(val[3])),
            ),
        )

    def _ser_config_row(self, c) -> tuple:
        return (
            c[0], c[1], tuple(sorted(c[2])), tuple(sorted(c[3])),
            tuple(sorted(c[4])), c[5],
        )

    def _perm_entry(self, e, sigma) -> tuple:
        cmd, term, val = e
        if cmd == APPEND_CMD:
            return e
        ps = lambda fs: frozenset(sigma[x] for x in fs)
        if cmd == NEW_CMD:
            return (cmd, term, (val[0], ps(val[1])))
        return (cmd, term, (val[0], ps(val[1]), ps(val[2]), ps(val[3])))

    def _perm_config_row(self, c, sigma) -> tuple:
        ps = lambda fs: frozenset(sigma[x] for x in fs)
        return (c[0], c[1], ps(c[2]), ps(c[3]), ps(c[4]), c[5])

    # ---------- invariants (:1058-1140) ----------

    def _cfg_members_of(self, c) -> frozenset:
        return c[2]

    # no_log_divergence / leader_has_all_acked_values /
    # committed_entries_reach_majority: shared in ConfigOracleBase
    # (spec formulas :1066-1074/:1109-1125/:1129-1140)

    def max_one_reconfiguration_at_a_time(self, st) -> bool:
        """MaxOneReconfigurationAtATime — :1080-1101: two same-type config
        commands must have the opposite type strictly between them."""
        for command, other in ((OLDNEW_CMD, NEW_CMD), (NEW_CMD, OLDNEW_CMD)):
            for i in range(self.S):
                log_i = st["log"][i]
                if len(log_i) <= 1:
                    continue
                idxs = [
                    k for k in range(1, len(log_i) + 1) if log_i[k - 1][0] == command
                ]
                for a in range(len(idxs)):
                    for b in range(a + 1, len(idxs)):
                        ind1, ind2 = idxs[a], idxs[b]
                        if ind2 - ind1 == 1:
                            return False
                        if not any(
                            log_i[k - 1][0] == other
                            for k in range(ind1 + 1, ind2)
                        ):
                            return False
        return True

    INVARIANTS = {
        "NoLogDivergence": ConfigOracleBase.no_log_divergence,
        "MaxOneReconfigurationAtATime": max_one_reconfiguration_at_a_time,
        "LeaderHasAllAckedValues": ConfigOracleBase.leader_has_all_acked_values,
        "CommittedEntriesReachMajority":
            ConfigOracleBase.committed_entries_reach_majority,
        "TestInv": lambda self, st: True,
    }

