"""Independent pure-Python interpreter of standard-raft/Raft.tla.

This is the differential-testing ground truth for the TPU kernels (TLC is
an external Java tool and is not vendored; see SURVEY.md §4). It is written
directly against the TLA+ text — NOT against the JAX lowering — so that the
two implementations only agree if both match the spec.

State format (shared with RaftModel.decode/encode): a dict of
  currentTerm: tuple[int], state: tuple[int 0/1/2], votedFor: tuple[int|None],
  votesGranted: tuple[frozenset[int]], log: tuple[tuple[(term, value)]],
  commitIndex: tuple[int], nextIndex/matchIndex: tuple[tuple[int]],
  pendingResponse: tuple[tuple[bool]], messages: frozenset[(record, count)],
  acked: tuple[None|False|True], electionCtr: int, restartCtr: int
with servers and values as 0-based ints and message records as tuples of
sorted (field, value) pairs.
"""

from __future__ import annotations

import itertools

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2


def oracle_for(params) -> "RaftOracle":
    """Build the oracle matching a models.raft.RaftParams (same variant knobs)."""
    return RaftOracle(
        params.n_servers,
        params.n_values,
        params.max_elections,
        params.max_restarts,
        election_quorum=params.election_quorum,
        replication_quorum=params.replication_quorum,
        strict_send_once=params.strict_send_once,
        has_pending_response=params.has_pending_response,
        trunc_term_mismatch=params.trunc_term_mismatch,
        has_fsync=params.has_fsync,
        fsync_leader_before_ae=params.fsync_leader_before_ae,
        fsync_leader_quorum=params.fsync_leader_quorum,
        fsync_follower_reply=params.fsync_follower_reply,
    )


def rec(**kw) -> tuple:
    return tuple(sorted(kw.items()))


def _last_term(log) -> int:
    """LastTerm(xlog) — Raft.tla:126."""
    return log[-1][0] if log else 0


class RaftOracle:
    """Variant knobs (defaults = standard Raft; see RaftParams in
    models/raft.py for the FlexibleRaft sources):
    count-based quorums, strict send-once messaging, absent
    pendingResponse, term-mismatch NeedsTruncation."""

    def __init__(
        self,
        n_servers: int,
        n_values: int,
        max_elections: int,
        max_restarts: int,
        election_quorum: int | None = None,
        replication_quorum: int | None = None,
        strict_send_once: bool = False,
        has_pending_response: bool = True,
        trunc_term_mismatch: bool = False,
        has_fsync: bool = False,
        fsync_leader_before_ae: bool = False,
        fsync_leader_quorum: bool = False,
        fsync_follower_reply: bool = False,
    ):
        self.S = n_servers
        self.V = n_values
        self.max_elections = max_elections
        self.max_restarts = max_restarts
        self.election_quorum = election_quorum
        self.replication_quorum = replication_quorum
        self.strict_send_once = strict_send_once
        self.has_pending_response = has_pending_response
        self.trunc_term_mismatch = trunc_term_mismatch
        self.has_fsync = has_fsync
        self.fsync_leader_before_ae = fsync_leader_before_ae
        self.fsync_leader_quorum = fsync_leader_quorum
        self.fsync_follower_reply = fsync_follower_reply

    # ---------- state helpers ----------

    def init_state(self) -> dict:
        """Init — Raft.tla:213-218 (RaftFsync.tla:189-194 adds fsyncIndex)."""
        S, V = self.S, self.V
        extra = {"fsyncIndex": (0,) * S} if self.has_fsync else {}
        return extra | {
            "currentTerm": (1,) * S,
            "state": (FOLLOWER,) * S,
            "votedFor": (None,) * S,
            "votesGranted": (frozenset(),) * S,
            "log": ((),) * S,
            "commitIndex": (0,) * S,
            "nextIndex": ((1,) * S,) * S,
            "matchIndex": ((0,) * S,) * S,
            "pendingResponse": ((False,) * S,) * S,
            "messages": frozenset(),
            "acked": (None,) * V,
            "electionCtr": 0,
            "restartCtr": 0,
        }

    @staticmethod
    def _msgs(st) -> dict:
        return dict(st["messages"])

    @staticmethod
    def _with(st, **updates) -> dict:
        out = dict(st)
        out.update(updates)
        return out

    @staticmethod
    def _set(tup, i, val) -> tuple:
        lst = list(tup)
        lst[i] = val
        return tuple(lst)

    @classmethod
    def _set2(cls, mat, i, j, val) -> tuple:
        return cls._set(mat, i, cls._set(mat[i], j, val))

    # ---------- message-bag helpers (Raft.tla:129-176) ----------

    @staticmethod
    def _send_no_restriction(msgs, m):
        msgs = dict(msgs)
        msgs[m] = msgs.get(m, 0) + 1
        return msgs

    @staticmethod
    def _send_once(msgs, m):
        if m in msgs:  # in DOMAIN (even at count 0): permanently disabled
            return None
        msgs = dict(msgs)
        msgs[m] = 1
        return msgs

    def _send(self, msgs, m):
        """Send — Raft.tla:145-149: empty AppendEntriesRequest is send-once.
        FlexibleRaft (FlexibleRaft.tla:127-129): everything is send-once."""
        if self.strict_send_once:
            return self._send_once(msgs, m)
        d = dict(m)
        if d["mtype"] == "AppendEntriesRequest" and d["mentries"] == ():
            return self._send_once(msgs, m)
        return self._send_no_restriction(msgs, m)

    @staticmethod
    def _send_multiple_once(msgs, ms):
        if any(m in msgs for m in ms):
            return None
        msgs = dict(msgs)
        for m in ms:
            msgs[m] = 1
        return msgs

    def _reply(self, msgs, response, request):
        """Reply — Raft.tla:170-176. FlexibleRaft (FlexibleRaft.tla:148-151)
        is disabled (None) when the response already exists."""
        assert msgs.get(request, 0) > 0
        if self.strict_send_once and response in msgs:
            return None
        msgs = dict(msgs)
        msgs[request] -= 1
        msgs[response] = msgs.get(response, 0) + 1
        return msgs

    @staticmethod
    def _discard(msgs, m):
        assert msgs.get(m, 0) > 0
        msgs = dict(msgs)
        msgs[m] -= 1
        return msgs

    def _receivable(self, st, m, mtype: str, equal_term: bool) -> bool:
        """ReceivableMessage — Raft.tla:181-187."""
        msgs = self._msgs(st)
        if msgs.get(m, 0) <= 0:
            return False
        d = dict(m)
        if d["mtype"] != mtype:
            return False
        ct = st["currentTerm"][d["mdest"]]
        return d["mterm"] == ct if equal_term else d["mterm"] <= ct

    def _domain(self, st):
        """DOMAIN messages (count-0 records included), deterministic order."""
        return sorted(dict(st["messages"]).keys())

    # ---------- actions (Next order, Raft.tla:527-539) ----------

    def successors(self, st) -> list[tuple[str, dict]]:
        out = []
        S, V = self.S, self.V
        for i in range(S):
            s2 = self.restart(st, i)
            if s2 is not None:
                out.append((f"Restart({i})", s2))
        if self.has_fsync:
            # RaftFsync Next order (RaftFsync.tla:522-536)
            for i in range(S):
                s2 = self.timeout(st, i)
                if s2 is not None:
                    out.append((f"Timeout({i})", s2))
            for i in range(S):
                for j in range(S):
                    if i != j:
                        s2 = self.request_vote_pair(st, i, j)
                        if s2 is not None:
                            out.append((f"RequestVote({i},{j})", s2))
        else:
            for i in range(S):
                s2 = self.request_vote(st, i)
                if s2 is not None:
                    out.append((f"RequestVote({i})", s2))
        for i in range(S):
            s2 = self.become_leader(st, i)
            if s2 is not None:
                out.append((f"BecomeLeader({i})", s2))
        for i in range(S):
            for v in range(V):
                s2 = self.client_request(st, i, v)
                if s2 is not None:
                    out.append((f"ClientRequest({i},{v})", s2))
        for i in range(S):
            s2 = self.advance_commit_index(st, i)
            if s2 is not None:
                out.append((f"AdvanceCommitIndex({i})", s2))
        for i in range(S):
            for j in range(S):
                if i != j:
                    s2 = self.append_entries(st, i, j)
                    if s2 is not None:
                        out.append((f"AppendEntries({i},{j})", s2))
        if self.has_fsync:
            for i in range(S):
                s2 = self.advance_fsync_index(st, i)
                if s2 is not None:
                    out.append((f"AdvanceFsyncIndex({i})", s2))
        for m in self._domain(st):
            s2 = self.update_term(st, m)
            if s2 is not None:
                out.append((f"UpdateTerm[{dict(m)['mdest']}]", s2))
        for m in self._domain(st):
            s2 = self.handle_request_vote_request(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteRequest", s2))
        for m in self._domain(st):
            s2 = self.handle_request_vote_response(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteResponse", s2))
        for m in self._domain(st):
            s2 = self.reject_append_entries_request(st, m)
            if s2 is not None:
                out.append(("RejectAppendEntriesRequest", s2))
        for m in self._domain(st):
            s2 = self.accept_append_entries_request(st, m)
            if s2 is not None:
                out.append(("AcceptAppendEntriesRequest", s2))
        for m in self._domain(st):
            s2 = self.handle_append_entries_response(st, m)
            if s2 is not None:
                out.append(("HandleAppendEntriesResponse", s2))
        return out

    def restart(self, st, i):
        """Restart(i) — Raft.tla:226-235; RaftFsync.tla:203-218 truncates
        the log to fsyncIndex."""
        if st["restartCtr"] >= self.max_restarts:
            return None
        S = self.S
        extra = {}
        if self.has_fsync:
            fi = st["fsyncIndex"][i]
            log_i = st["log"][i]
            if fi == 0:
                new_log = ()
            elif len(log_i) > 0 and len(log_i) > fi:
                new_log = log_i[:fi]
            else:
                new_log = log_i
            extra["log"] = self._set(st["log"], i, new_log)
        return self._with(
            st,
            state=self._set(st["state"], i, FOLLOWER),
            votesGranted=self._set(st["votesGranted"], i, frozenset()),
            nextIndex=self._set(st["nextIndex"], i, (1,) * S),
            matchIndex=self._set(st["matchIndex"], i, (0,) * S),
            pendingResponse=self._set(st["pendingResponse"], i, (False,) * S),
            commitIndex=self._set(st["commitIndex"], i, 0),
            restartCtr=st["restartCtr"] + 1,
            **extra,
        )

    def timeout(self, st, i):
        """Timeout(i) — RaftFsync.tla:222-230."""
        if st["electionCtr"] >= self.max_elections:
            return None
        if st["state"][i] not in (FOLLOWER, CANDIDATE):
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, CANDIDATE),
            currentTerm=self._set(st["currentTerm"], i, st["currentTerm"][i] + 1),
            votedFor=self._set(st["votedFor"], i, i),
            votesGranted=self._set(st["votesGranted"], i, frozenset({i})),
            electionCtr=st["electionCtr"] + 1,
        )

    def request_vote_pair(self, st, i, j):
        """RequestVote(i, j) — RaftFsync.tla:234-243."""
        if i == j or st["state"][i] != CANDIDATE:
            return None
        m = rec(
            mtype="RequestVoteRequest",
            mterm=st["currentTerm"][i],
            mlastLogTerm=_last_term(st["log"][i]),
            mlastLogIndex=len(st["log"][i]),
            msource=i,
            mdest=j,
        )
        msgs = self._send_once(self._msgs(st), m)  # Send (RaftFsync.tla:132-134)
        if msgs is None:
            return None
        return self._with(st, messages=frozenset(msgs.items()))

    def advance_fsync_index(self, st, i):
        """AdvanceFsyncIndex(i) — RaftFsync.tla:339-343."""
        if st["fsyncIndex"][i] >= len(st["log"][i]):
            return None
        return self._with(
            st, fsyncIndex=self._set(st["fsyncIndex"], i, st["fsyncIndex"][i] + 1)
        )

    def request_vote(self, st, i):
        """RequestVote(i) — Raft.tla:242-257."""
        if st["electionCtr"] >= self.max_elections:
            return None
        if st["state"][i] not in (FOLLOWER, CANDIDATE):
            return None
        new_term = st["currentTerm"][i] + 1
        ms = {
            rec(
                mtype="RequestVoteRequest",
                mterm=new_term,
                mlastLogTerm=_last_term(st["log"][i]),
                mlastLogIndex=len(st["log"][i]),
                msource=i,
                mdest=j,
            )
            for j in range(self.S)
            if j != i
        }
        msgs = self._send_multiple_once(self._msgs(st), ms)
        if msgs is None:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, CANDIDATE),
            currentTerm=self._set(st["currentTerm"], i, new_term),
            votedFor=self._set(st["votedFor"], i, i),
            votesGranted=self._set(st["votesGranted"], i, frozenset({i})),
            electionCtr=st["electionCtr"] + 1,
            messages=frozenset(msgs.items()),
        )

    def become_leader(self, st, i):
        """BecomeLeader(i) — Raft.tla:289-300."""
        if st["state"][i] != CANDIDATE:
            return None
        if self.election_quorum is not None:
            if len(st["votesGranted"][i]) < self.election_quorum:
                return None  # FlexibleRaft.tla:262
        elif 2 * len(st["votesGranted"][i]) <= self.S:  # Quorum (Raft.tla:123)
            return None
        S = self.S
        n = len(st["log"][i]) + 1
        return self._with(
            st,
            state=self._set(st["state"], i, LEADER),
            nextIndex=self._set(st["nextIndex"], i, (n,) * S),
            matchIndex=self._set(st["matchIndex"], i, (0,) * S),
            pendingResponse=self._set(st["pendingResponse"], i, (False,) * S),
        )

    def client_request(self, st, i, v):
        """ClientRequest(i, v) — Raft.tla:304-313."""
        if st["state"][i] != LEADER or st["acked"][v] is not None:
            return None
        entry = (st["currentTerm"][i], v)
        return self._with(
            st,
            log=self._set(st["log"], i, st["log"][i] + (entry,)),
            acked=self._set(st["acked"], v, False),
        )

    def advance_commit_index(self, st, i):
        """AdvanceCommitIndex(i) — Raft.tla:320-344."""
        if st["state"][i] != LEADER:
            return None
        S = self.S
        log_i = st["log"][i]
        mi = st["matchIndex"][i]
        def _quorum(n: int) -> bool:
            if self.replication_quorum is not None:
                return n >= self.replication_quorum  # FlexibleRaft.tla:296
            return 2 * n > S

        def _agree(idx: int) -> set:
            """Agree(index) — Raft.tla:323-324; RaftFsync.tla:313-315
            excludes the leader itself above its fsyncIndex."""
            base = {k for k in range(S) if mi[k] >= idx}
            if (
                self.has_fsync
                and self.fsync_leader_quorum
                and idx > st["fsyncIndex"][i]
            ):
                return base
            return {i} | base

        agree_indexes = [
            idx for idx in range(1, len(log_i) + 1) if _quorum(len(_agree(idx)))
        ]
        ci = st["commitIndex"][i]
        if agree_indexes and log_i[max(agree_indexes) - 1][0] == st["currentTerm"][i]:
            new_ci = max(agree_indexes)
        else:
            new_ci = ci
        if ci >= new_ci:
            return None
        committed_vals = {log_i[idx - 1][1] for idx in range(ci + 1, new_ci + 1)}
        acked = tuple(
            (v in committed_vals) if st["acked"][v] is False else st["acked"][v]
            for v in range(self.V)
        )
        return self._with(
            st, commitIndex=self._set(st["commitIndex"], i, new_ci), acked=acked
        )

    def append_entries(self, st, i, j):
        """AppendEntries(i, j) — Raft.tla:263-285."""
        if i == j or st["state"][i] != LEADER:
            return None
        if self.has_pending_response and st["pendingResponse"][i][j]:
            return None
        log_i = st["log"][i]
        ni = st["nextIndex"][i][j]
        prev_index = ni - 1
        prev_term = log_i[prev_index - 1][0] if prev_index > 0 else 0
        last_entry = min(len(log_i), ni)
        entries = tuple(log_i[ni - 1 : last_entry])
        if self.has_fsync and self.fsync_leader_before_ae:
            # LeaderFsyncBeforeAppendEntries gate (RaftFsync.tla:261-263)
            if st["fsyncIndex"][i] < last_entry:
                return None
        m = rec(
            mtype="AppendEntriesRequest",
            mterm=st["currentTerm"][i],
            mprevLogIndex=prev_index,
            mprevLogTerm=prev_term,
            mentries=entries,
            mcommitIndex=min(st["commitIndex"][i], last_entry),
            msource=i,
            mdest=j,
        )
        msgs = self._send(self._msgs(st), m)
        if msgs is None:
            return None
        pending = st["pendingResponse"]
        if self.has_pending_response:
            pending = self._set2(pending, i, j, True)
        return self._with(
            st, pendingResponse=pending, messages=frozenset(msgs.items())
        )

    def update_term(self, st, m):
        """UpdateTerm — Raft.tla:348-355 (any DOMAIN record, count-0 included)."""
        d = dict(m)
        i = d["mdest"]
        if d["mterm"] <= st["currentTerm"][i]:
            return None
        return self._with(
            st,
            currentTerm=self._set(st["currentTerm"], i, d["mterm"]),
            state=self._set(st["state"], i, FOLLOWER),
            votedFor=self._set(st["votedFor"], i, None),
        )

    def handle_request_vote_request(self, st, m):
        """HandleRequestVoteRequest — Raft.tla:360-381."""
        if not self._receivable(st, m, "RequestVoteRequest", equal_term=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        log_ok = d["mlastLogTerm"] > _last_term(st["log"][i]) or (
            d["mlastLogTerm"] == _last_term(st["log"][i])
            and d["mlastLogIndex"] >= len(st["log"][i])
        )
        grant = (
            d["mterm"] == st["currentTerm"][i]
            and log_ok
            and st["votedFor"][i] in (None, j)
        )
        resp = rec(
            mtype="RequestVoteResponse",
            mterm=st["currentTerm"][i],
            mvoteGranted=grant,
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(
            st,
            votedFor=self._set(st["votedFor"], i, j) if grant else st["votedFor"],
            messages=frozenset(msgs.items()),
        )

    def handle_request_vote_response(self, st, m):
        """HandleRequestVoteResponse — Raft.tla:386-401."""
        if not self._receivable(st, m, "RequestVoteResponse", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        vg = st["votesGranted"]
        if d["mvoteGranted"]:
            vg = self._set(vg, i, vg[i] | {j})
        msgs = self._discard(self._msgs(st), m)
        return self._with(st, votesGranted=vg, messages=frozenset(msgs.items()))

    def _log_ok(self, st, d) -> bool:
        """LogOk — Raft.tla:406-410."""
        i = d["mdest"]
        return d["mprevLogIndex"] == 0 or (
            0 < d["mprevLogIndex"] <= len(st["log"][i])
            and d["mprevLogTerm"] == st["log"][i][d["mprevLogIndex"] - 1][0]
        )

    def reject_append_entries_request(self, st, m):
        """RejectAppendEntriesRequest — Raft.tla:412-430."""
        if not self._receivable(st, m, "AppendEntriesRequest", equal_term=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        ct = st["currentTerm"][i]
        if not (
            d["mterm"] < ct
            or (
                d["mterm"] == ct
                and st["state"][i] == FOLLOWER
                and not self._log_ok(st, d)
            )
        ):
            return None
        resp = rec(
            mtype="AppendEntriesResponse",
            mterm=ct,
            msuccess=False,
            mmatchIndex=0,
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=frozenset(msgs.items()))

    def accept_append_entries_request(self, st, m):
        """AcceptAppendEntriesRequest — Raft.tla:454-485."""
        if not self._receivable(st, m, "AppendEntriesRequest", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] not in (FOLLOWER, CANDIDATE) or not self._log_ok(st, d):
            return None
        log_i = st["log"][i]
        prev = d["mprevLogIndex"]
        index = prev + 1
        entries = d["mentries"]
        can_append = entries != () and len(log_i) == prev  # CanAppend (Raft.tla:438-440)
        if self.trunc_term_mismatch:
            # NeedsTruncation (FlexibleRaft.tla:413-416)
            needs_trunc = (
                entries != ()
                and len(log_i) >= index
                and log_i[index - 1][0] != entries[0][0]
            )
        else:
            needs_trunc = (entries != () and len(log_i) >= index) or (
                entries == () and len(log_i) > prev
            )  # NeedsTruncation (Raft.tla:445-449)
        if can_append:
            new_log = log_i + (entries[0],)
        elif needs_trunc and entries != ():
            new_log = log_i[:prev] + (entries[0],)
        elif needs_trunc:
            new_log = log_i[:prev]
        else:
            new_log = log_i
        resp = rec(
            mtype="AppendEntriesResponse",
            mterm=st["currentTerm"][i],
            msuccess=True,
            mmatchIndex=prev + len(entries),
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        extra = {}
        if self.has_fsync and self.fsync_follower_reply:
            # fsyncIndex := Len(new_log) (RaftFsync.tla:468-470)
            extra["fsyncIndex"] = self._set(st["fsyncIndex"], i, len(new_log))
        return self._with(
            st,
            state=self._set(st["state"], i, FOLLOWER),
            commitIndex=self._set(st["commitIndex"], i, d["mcommitIndex"]),
            log=self._set(st["log"], i, new_log),
            messages=frozenset(msgs.items()),
            **extra,
        )

    def handle_append_entries_response(self, st, m):
        """HandleAppendEntriesResponse — Raft.tla:490-505."""
        if not self._receivable(st, m, "AppendEntriesResponse", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        ni, mi = st["nextIndex"], st["matchIndex"]
        if d["msuccess"]:
            ni = self._set2(ni, i, j, d["mmatchIndex"] + 1)
            mi = self._set2(mi, i, j, d["mmatchIndex"])
        else:
            ni = self._set2(ni, i, j, max(ni[i][j] - 1, 1))
        msgs = self._discard(self._msgs(st), m)
        pending = st["pendingResponse"]
        if self.has_pending_response:
            pending = self._set2(pending, i, j, False)
        return self._with(
            st,
            nextIndex=ni,
            matchIndex=mi,
            pendingResponse=pending,
            messages=frozenset(msgs.items()),
        )

    # ---------- VIEW + SYMMETRY (Raft.tla:115-116) ----------

    def serialize_view(self, st) -> tuple:
        """Orderable serialization of the VIEW projection (drops aux vars).
        RaftFsync's view includes fsyncIndex (RaftFsync.tla:117)."""
        return ((st["fsyncIndex"],) if self.has_fsync else ()) + (
            st["currentTerm"],
            st["state"],
            tuple(-1 if v is None else v for v in st["votedFor"]),
            tuple(tuple(sorted(vs)) for vs in st["votesGranted"]),
            st["log"],
            st["commitIndex"],
            st["nextIndex"],
            st["matchIndex"],
            st["pendingResponse"],
            tuple(sorted(st["messages"])),
        )

    def serialize_full(self, st) -> tuple:
        """Orderable serialization of the FULL state (view + aux vars)."""
        ack = {None: -1, False: 0, True: 1}
        return self.serialize_view(st) + (
            tuple(ack[a] for a in st["acked"]),
            st["electionCtr"],
            st["restartCtr"],
        )

    def permute(self, st, sigma) -> dict:
        """Apply a server permutation (old index -> new index) to the state."""
        S = self.S
        inv = [0] * S
        for old, new in enumerate(sigma):
            inv[new] = old

        def prow(t):
            return tuple(t[inv[k]] for k in range(S))

        def pmsg(m):
            d = dict(m)
            d["msource"] = sigma[d["msource"]]
            d["mdest"] = sigma[d["mdest"]]
            return rec(**d)

        extra = {"fsyncIndex": prow(st["fsyncIndex"])} if self.has_fsync else {}
        return self._with(
            st,
            currentTerm=prow(st["currentTerm"]),
            state=prow(st["state"]),
            **extra,
            votedFor=tuple(
                None if v is None else sigma[v] for v in prow(st["votedFor"])
            ),
            votesGranted=tuple(
                frozenset(sigma[j] for j in vs) for vs in prow(st["votesGranted"])
            ),
            log=prow(st["log"]),
            commitIndex=prow(st["commitIndex"]),
            nextIndex=tuple(prow(row) for row in prow(st["nextIndex"])),
            matchIndex=tuple(prow(row) for row in prow(st["matchIndex"])),
            pendingResponse=tuple(prow(row) for row in prow(st["pendingResponse"])),
            messages=frozenset(
                (pmsg(m), c) for m, c in st["messages"]
            ),
        )

    def canon(self, st, symmetry: bool = True) -> tuple:
        """Canonical dedup key: min over server permutations of the view."""
        if not symmetry:
            return self.serialize_view(st)
        return min(
            self.serialize_view(self.permute(st, list(sigma)))
            for sigma in itertools.permutations(range(self.S))
        )

    # ---------- invariants (Raft.tla:588-636) ----------

    def no_log_divergence(self, st) -> bool:
        for s1 in range(self.S):
            for s2 in range(self.S):
                if s1 == s2:
                    continue
                mci = min(st["commitIndex"][s1], st["commitIndex"][s2])
                for idx in range(1, mci + 1):
                    if st["log"][s1][idx - 1] != st["log"][s2][idx - 1]:
                        return False
        return True

    def leader_has_all_acked_values(self, st) -> bool:
        for v in range(self.V):
            if st["acked"][v] is not True:
                continue
            for i in range(self.S):
                if st["state"][i] != LEADER:
                    continue
                if any(
                    st["currentTerm"][l] > st["currentTerm"][i]
                    for l in range(self.S)
                    if l != i
                ):
                    continue
                if not any(e[1] == v for e in st["log"][i]):
                    return False
        return True

    def committed_entries_reach_majority(self, st) -> bool:
        leaders = [
            i
            for i in range(self.S)
            if st["state"][i] == LEADER and st["commitIndex"][i] > 0
        ]
        if not leaders:
            return True
        need = self.S // 2 + 1
        for i in leaders:
            ci = st["commitIndex"][i]
            entry = st["log"][i][ci - 1]
            n = sum(
                1
                for j in range(self.S)
                if len(st["log"][j]) >= ci and st["log"][j][ci - 1] == entry
            )
            if n >= need:
                return True
        return False

    INVARIANTS = {
        "NoLogDivergence": no_log_divergence,
        "LeaderHasAllAckedValues": leader_has_all_acked_values,
        "CommittedEntriesReachMajority": committed_entries_reach_majority,
        "TestInv": lambda self, st: True,
    }

    # ---------- BFS model checking ----------

    def bfs(
        self,
        invariants: tuple[str, ...] = ("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry: bool = True,
        max_depth: int | None = None,
        max_states: int | None = None,
        time_budget_s: float | None = None,
    ) -> dict:
        """Exhaustive BFS with TLC semantics: dedup on the canonicalized
        VIEW, invariants checked on every distinct state."""
        import time

        t0 = time.perf_counter()
        init = self.init_state()
        seen = {self.canon(init, symmetry)}
        frontier = [init]
        total = 1
        distinct = 1
        depth_counts = [1]
        violation = None
        depth = 0
        while frontier and violation is None:
            if max_depth is not None and depth >= max_depth:
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                break
            next_frontier = []
            for st in frontier:
                for _label, s2 in self.successors(st):
                    total += 1
                    key = self.canon(s2, symmetry)
                    if key in seen:
                        continue
                    seen.add(key)
                    distinct += 1
                    for inv in invariants:
                        if not self.INVARIANTS[inv](self, s2):
                            violation = {"invariant": inv, "state": s2, "depth": depth + 1}
                            break
                    next_frontier.append(s2)
                    if violation or (max_states and distinct >= max_states):
                        break
                if violation or (max_states and distinct >= max_states):
                    break
                if (
                    time_budget_s is not None
                    and (total & 0x3FF) < 8
                    and time.perf_counter() - t0 > time_budget_s
                ):
                    break
            frontier = next_frontier
            if frontier:
                depth_counts.append(len(frontier))
            depth += 1
        return {
            "distinct": distinct,
            "total": total,
            "depth_counts": depth_counts,
            "violation": violation,
        }
