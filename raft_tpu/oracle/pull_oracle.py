"""Independent pure-Python interpreter of pull-raft/PullRaft.tla and
pull-raft/PullRaftVariant2.tla.

Differential-testing ground truth for the TPU lowering in
models/pull_raft.py, written directly against the TLA+ text (reference
``/root/reference/specifications/pull-raft/PullRaft.tla``, 631 lines;
``PullRaftVariant2.tla``, 648 lines) — NOT against the JAX kernels.

Key structural deltas vs. core Raft (see SURVEY.md §2.1):
  - followers PULL from the leader (`SendPullEntriesRequest`), the leader
    never pushes;
  - `leader` replaces/augments `votedFor` (`PullRaft.tla:92`): in PullRaft a
    vote immediately sets `leader`; Variant2 keeps both (`:78,81`) and
    followers wait for a `LeaderNotifyRequest`;
  - ALL sends are strictly send-once (`PullRaft.tla:137-143`) and replies
    require the response to be absent (`:158-161`);
  - `view` includes `acked` in PullRaft (`PullRaft.tla:123`) but NOT in
    Variant2 (`PullRaftVariant2.tla:114`);
  - Variant2 tracks `votesLastEntry` (`PullRaftVariant2.tla:98`) so
    `BecomeLeader` can embed per-peer `mlastCommonEntry` in the notify
    (`:361-379`) and `LearnOfLeader` may truncate (`:398-410`).

State dict format (shared with PullRaftModel.decode/encode):
  currentTerm, state, leader (int|None per server), [votedFor (V2)],
  votesGranted (frozensets), [votesLastEntry (V2): tuple[tuple[None|(idx,term)]]],
  log, commitIndex, matchIndex, messages, acked, electionCtr, restartCtr.
"""

from __future__ import annotations

import itertools

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2


def rec(**kw) -> tuple:
    return tuple(sorted(kw.items()))


def _last_term(log) -> int:
    """LastTerm(xlog) — PullRaft.tla:134."""
    return log[-1][0] if log else 0


def compare_entries(index1, term1, index2, term2) -> int:
    """CompareEntries — PullRaft.tla:203-207 (term precedence)."""
    if term1 > term2:
        return 1
    if term1 == term2 and index1 > index2:
        return 1
    if term1 == term2 and index1 == index2:
        return 0
    return -1


def last_common_entry(log_i, last_index, last_term) -> tuple[int, int]:
    """LastCommonEntry(i, lastIndex, lastTerm) — PullRaft.tla:211-226:
    the highest entry of log_i at-or-below (lastIndex, lastTerm) in the
    CompareEntries order; (0, 0) when none."""
    best = 0
    for idx in range(1, len(log_i) + 1):
        if compare_entries(idx, log_i[idx - 1][0], last_index, last_term) <= 0:
            best = idx
    if best == 0:
        return (0, 0)
    return (best, log_i[best - 1][0])


class PullRaftOracle:
    def __init__(
        self,
        n_servers: int,
        n_values: int,
        max_elections: int,
        max_restarts: int,
        variant2: bool = False,
    ):
        self.S = n_servers
        self.V = n_values
        self.max_elections = max_elections
        self.max_restarts = max_restarts
        self.variant2 = variant2

    # ---------- state helpers ----------

    def init_state(self) -> dict:
        """Init — PullRaft.tla:231-250 (Variant2: adds votedFor,
        votesLastEntry, PullRaftVariant2.tla:222-243)."""
        S, V = self.S, self.V
        extra = (
            {"votedFor": (None,) * S, "votesLastEntry": ((None,) * S,) * S}
            if self.variant2
            else {}
        )
        return extra | {
            "currentTerm": (1,) * S,
            "state": (FOLLOWER,) * S,
            "leader": (None,) * S,
            "votesGranted": (frozenset(),) * S,
            "log": ((),) * S,
            "commitIndex": (0,) * S,
            "matchIndex": ((0,) * S,) * S,
            "messages": frozenset(),
            "acked": (None,) * V,
            "electionCtr": 0,
            "restartCtr": 0,
        }

    @staticmethod
    def _msgs(st) -> dict:
        return dict(st["messages"])

    @staticmethod
    def _with(st, **updates) -> dict:
        out = dict(st)
        out.update(updates)
        return out

    @staticmethod
    def _set(tup, i, val) -> tuple:
        lst = list(tup)
        lst[i] = val
        return tuple(lst)

    @classmethod
    def _set2(cls, mat, i, j, val) -> tuple:
        return cls._set(mat, i, cls._set(mat[i], j, val))

    # ---------- message-bag helpers (PullRaft.tla:137-172) ----------

    @staticmethod
    def _send(msgs, m):
        """Send — PullRaft.tla:137-139: strictly send-once."""
        if m in msgs:
            return None
        msgs = dict(msgs)
        msgs[m] = 1
        return msgs

    @staticmethod
    def _send_multiple(msgs, ms):
        """SendMultiple — PullRaft.tla:141-143: all must be absent."""
        if any(m in msgs for m in ms):
            return None
        msgs = dict(msgs)
        for m in ms:
            msgs[m] = 1
        return msgs

    @staticmethod
    def _reply(msgs, response, request):
        """Reply — PullRaft.tla:158-161: response must be absent."""
        assert msgs.get(request, 0) > 0
        if response in msgs:
            return None
        msgs = dict(msgs)
        msgs[request] -= 1
        msgs[response] = 1
        return msgs

    @staticmethod
    def _discard(msgs, m):
        """Discard — PullRaft.tla:152-155."""
        assert msgs.get(m, 0) > 0
        msgs = dict(msgs)
        msgs[m] -= 1
        return msgs

    def _receivable(self, st, m, mtype: str, equal_term: bool) -> bool:
        """ReceivableMessage — PullRaft.tla:166-172."""
        msgs = self._msgs(st)
        if msgs.get(m, 0) <= 0:
            return False
        d = dict(m)
        if d["mtype"] != mtype:
            return False
        ct = st["currentTerm"][d["mdest"]]
        return d["mterm"] == ct if equal_term else d["mterm"] <= ct

    def _domain(self, st):
        # sort on the None-normalized form: Variant2 notify records mix
        # mlastCommonEntry=None and (index, term), which are not orderable
        return sorted(
            dict(st["messages"]).keys(),
            key=lambda m: tuple((k, (-1, -1) if v is None else v) for k, v in m),
        )

    def _valid_pull_position(self, st, d) -> bool:
        """ValidPullPosition(i, m) — PullRaft.tla:192-196 (i = mdest)."""
        i = d["mdest"]
        if d["mlastLogIndex"] == 0:
            return True
        return (
            0 < d["mlastLogIndex"] <= len(st["log"][i])
            and d["mlastLogTerm"] == st["log"][i][d["mlastLogIndex"] - 1][0]
        )

    # ---------- actions (Next order, PullRaft.tla:542-558) ----------

    def successors(self, st) -> list[tuple[str, dict]]:
        out = []
        S, V = self.S, self.V
        for i in range(S):
            s2 = self.restart(st, i)
            if s2 is not None:
                out.append((f"Restart({i})", s2))
        for m in self._domain(st):
            s2 = self.update_term(st, m)
            if s2 is not None:
                out.append((f"UpdateTerm[{dict(m)['mdest']}]", s2))
        for i in range(S):
            s2 = self.request_vote(st, i)
            if s2 is not None:
                out.append((f"RequestVote({i})", s2))
        for m in self._domain(st):
            s2 = self.handle_request_vote_request(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteRequest", s2))
        for m in self._domain(st):
            s2 = self.handle_request_vote_response(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteResponse", s2))
        for i in range(S):
            s2 = self.become_leader(st, i)
            if s2 is not None:
                out.append((f"BecomeLeader({i})", s2))
        for i in range(S):
            for v in range(V):
                s2 = self.client_request(st, i, v)
                if s2 is not None:
                    out.append((f"ClientRequest({i},{v})", s2))
        for m in self._domain(st):
            s2 = self.reject_pull_entries_request(st, m)
            if s2 is not None:
                out.append(("RejectPullEntriesRequest", s2))
        for m in self._domain(st):
            s2 = self.accept_pull_entries_request(st, m)
            if s2 is not None:
                out.append(("AcceptPullEntriesRequest", s2))
        for m in self._domain(st):
            s2 = self.learn_of_leader(st, m)
            if s2 is not None:
                out.append(("LearnOfLeader", s2))
        for i in range(S):
            for j in range(S):
                if i != j:
                    s2 = self.send_pull_entries_request(st, i, j)
                    if s2 is not None:
                        out.append((f"SendPullEntriesRequest({i},{j})", s2))
        for m in self._domain(st):
            s2 = self.handle_success_pull_entries_response(st, m)
            if s2 is not None:
                out.append(("HandleSuccessPullEntriesResponse", s2))
        for m in self._domain(st):
            s2 = self.handle_fail_pull_entries_response(st, m)
            if s2 is not None:
                out.append(("HandleFailPullEntriesResponse", s2))
        return out

    def restart(self, st, i):
        """Restart(i) — PullRaft.tla:258-265 keeps currentTerm, leader, log;
        Variant2 (PullRaftVariant2.tla:251-260) keeps votedFor instead of
        leader and also clears votesLastEntry."""
        if st["restartCtr"] >= self.max_restarts:
            return None
        S = self.S
        extra = {}
        if self.variant2:
            extra["leader"] = self._set(st["leader"], i, None)
            extra["votesLastEntry"] = self._set(
                st["votesLastEntry"], i, (None,) * S
            )
        return self._with(
            st,
            state=self._set(st["state"], i, FOLLOWER),
            votesGranted=self._set(st["votesGranted"], i, frozenset()),
            matchIndex=self._set(st["matchIndex"], i, (0,) * S),
            commitIndex=self._set(st["commitIndex"], i, 0),
            restartCtr=st["restartCtr"] + 1,
            **extra,
        )

    def update_term(self, st, m):
        """UpdateTerm — PullRaft.tla:269-276 (resets leader; Variant2
        PullRaftVariant2.tla:264-272 also resets votedFor)."""
        d = dict(m)
        i = d["mdest"]
        if d["mterm"] <= st["currentTerm"][i]:
            return None
        extra = {"votedFor": self._set(st["votedFor"], i, None)} if self.variant2 else {}
        return self._with(
            st,
            currentTerm=self._set(st["currentTerm"], i, d["mterm"]),
            state=self._set(st["state"], i, FOLLOWER),
            leader=self._set(st["leader"], i, None),
            **extra,
        )

    def request_vote(self, st, i):
        """RequestVote(i) — PullRaft.tla:283-298: votes for itself by setting
        leader[i]=i; Variant2 (PullRaftVariant2.tla:279-295) sets votedFor=i
        and leader=Nil."""
        if st["electionCtr"] >= self.max_elections:
            return None
        if st["state"][i] not in (FOLLOWER, CANDIDATE):
            return None
        new_term = st["currentTerm"][i] + 1
        ms = {
            rec(
                mtype="RequestVoteRequest",
                mterm=new_term,
                mlastLogTerm=_last_term(st["log"][i]),
                mlastLogIndex=len(st["log"][i]),
                msource=i,
                mdest=j,
            )
            for j in range(self.S)
            if j != i
        }
        msgs = self._send_multiple(self._msgs(st), ms)
        if msgs is None:
            return None
        if self.variant2:
            extra = {
                "votedFor": self._set(st["votedFor"], i, i),
                "leader": self._set(st["leader"], i, None),
            }
        else:
            extra = {"leader": self._set(st["leader"], i, i)}
        return self._with(
            st,
            state=self._set(st["state"], i, CANDIDATE),
            currentTerm=self._set(st["currentTerm"], i, new_term),
            votesGranted=self._set(st["votesGranted"], i, frozenset({i})),
            electionCtr=st["electionCtr"] + 1,
            messages=frozenset(msgs.items()),
            **extra,
        )

    def handle_request_vote_request(self, st, m):
        """HandleRequestVoteRequest — PullRaft.tla:306-330 (grant tracked in
        `leader`); Variant2 (PullRaftVariant2.tla:303-326) tracks the grant
        in `votedFor` and the response carries the last log entry."""
        if not self._receivable(st, m, "RequestVoteRequest", equal_term=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        log_ok = d["mlastLogTerm"] > _last_term(st["log"][i]) or (
            d["mlastLogTerm"] == _last_term(st["log"][i])
            and d["mlastLogIndex"] >= len(st["log"][i])
        )
        vote_var = st["votedFor"] if self.variant2 else st["leader"]
        grant = (
            d["mterm"] == st["currentTerm"][i]
            and log_ok
            and vote_var[i] in (None, j)
        )
        kw = dict(
            mtype="RequestVoteResponse",
            mterm=st["currentTerm"][i],
            mvoteGranted=grant,
            msource=i,
            mdest=j,
        )
        if self.variant2:  # PullRaftVariant2.tla:320-321
            kw["mlastLogIndex"] = len(st["log"][i])
            kw["mlastLogTerm"] = _last_term(st["log"][i])
        msgs = self._reply(self._msgs(st), rec(**kw), m)
        if msgs is None:
            return None
        if grant:
            extra = (
                {"votedFor": self._set(st["votedFor"], i, j)}
                if self.variant2
                else {"leader": self._set(st["leader"], i, j)}
            )
        else:
            extra = {}
        return self._with(st, messages=frozenset(msgs.items()), **extra)

    def handle_request_vote_response(self, st, m):
        """HandleRequestVoteResponse — PullRaft.tla:335-350; Variant2
        (PullRaftVariant2.tla:331-349) also records votesLastEntry."""
        if not self._receivable(st, m, "RequestVoteResponse", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        vg = st["votesGranted"]
        extra = {}
        if d["mvoteGranted"]:
            vg = self._set(vg, i, vg[i] | {j})
            if self.variant2:
                extra["votesLastEntry"] = self._set2(
                    st["votesLastEntry"], i, j,
                    (d["mlastLogIndex"], d["mlastLogTerm"]),
                )
        msgs = self._discard(self._msgs(st), m)
        return self._with(
            st, votesGranted=vg, messages=frozenset(msgs.items()), **extra
        )

    def become_leader(self, st, i):
        """BecomeLeader(i) — PullRaft.tla:354-366 notifies only non-voters;
        Variant2 (PullRaftVariant2.tla:361-379) notifies ALL peers, embeds
        per-peer mlastCommonEntry, and sets leader[i]=i."""
        if st["state"][i] != CANDIDATE:
            return None
        if 2 * len(st["votesGranted"][i]) <= self.S:  # Quorum (PullRaft.tla:131)
            return None
        S = self.S
        if self.variant2:
            ms = set()
            for j in range(S):
                if j == i:
                    continue
                vle = st["votesLastEntry"][i][j]
                if vle is None:
                    lce = None
                else:
                    lce = last_common_entry(st["log"][i], vle[0], vle[1])
                ms.add(
                    rec(
                        mtype="LeaderNotifyRequest",
                        mterm=st["currentTerm"][i],
                        mlastCommonEntry=lce,
                        msource=i,
                        mdest=j,
                    )
                )
            extra = {"leader": self._set(st["leader"], i, i)}
        else:
            ms = {
                rec(
                    mtype="LeaderNotifyRequest",
                    mterm=st["currentTerm"][i],
                    msource=i,
                    mdest=j,
                )
                for j in range(S)
                if j not in st["votesGranted"][i]
            }
            extra = {}
        msgs = self._send_multiple(self._msgs(st), ms)
        if msgs is None:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, LEADER),
            matchIndex=self._set(st["matchIndex"], i, (0,) * S),
            messages=frozenset(msgs.items()),
            **extra,
        )

    def client_request(self, st, i, v):
        """ClientRequest(i, v) — PullRaft.tla:370-379."""
        if st["state"][i] != LEADER or st["acked"][v] is not None:
            return None
        entry = (st["currentTerm"][i], v)
        return self._with(
            st,
            log=self._set(st["log"], i, st["log"][i] + (entry,)),
            acked=self._set(st["acked"], v, False),
        )

    def learn_of_leader(self, st, m):
        """LearnOfLeader — PullRaft.tla:383-391; Variant2
        (PullRaftVariant2.tla:398-410) may truncate to mlastCommonEntry."""
        if not self._receivable(st, m, "LeaderNotifyRequest", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        msgs = self._discard(self._msgs(st), m)
        extra = {}
        if self.variant2:
            lce = d["mlastCommonEntry"]
            # NeedsTruncation (PullRaftVariant2.tla:171-173) + TruncateLog
            # (:176-179)
            if lce is not None and len(st["log"][i]) >= lce[0]:
                extra["log"] = self._set(st["log"], i, st["log"][i][: lce[0]])
        return self._with(
            st,
            leader=self._set(st["leader"], i, j),
            messages=frozenset(msgs.items()),
            **extra,
        )

    def send_pull_entries_request(self, st, i, j):
        """SendPullEntriesRequest(i, j) — PullRaft.tla:396-411."""
        if i == j or st["state"][i] != FOLLOWER or st["leader"][i] != j:
            return None
        log_i = st["log"][i]
        m = rec(
            mtype="PullEntriesRequest",
            mterm=st["currentTerm"][i],
            mlastLogIndex=len(log_i),
            mlastLogTerm=_last_term(log_i),
            msource=i,
            mdest=j,
        )
        msgs = self._send(self._msgs(st), m)
        if msgs is None:
            return None
        return self._with(st, messages=frozenset(msgs.items()))

    def reject_pull_entries_request(self, st, m):
        """RejectPullEntriesRequest — PullRaft.tla:418-436."""
        if not self._receivable(st, m, "PullEntriesRequest", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER or self._valid_pull_position(st, d):
            return None
        resp = rec(
            mtype="PullEntriesResponse",
            mterm=st["currentTerm"][i],
            msuccess=False,
            mlastCommonEntry=last_common_entry(
                st["log"][i], d["mlastLogIndex"], d["mlastLogTerm"]
            ),
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=frozenset(msgs.items()))

    def _new_commit_index(self, st, i, new_match_row) -> int:
        """NewCommitIndex(i, iMatchIndex) — PullRaft.tla:446-458."""
        S = self.S
        log_i = st["log"][i]
        agree_indexes = [
            idx
            for idx in range(1, len(log_i) + 1)
            if 2 * len({i} | {k for k in range(S) if new_match_row[k] >= idx}) > S
        ]
        if agree_indexes and log_i[max(agree_indexes) - 1][0] == st["currentTerm"][i]:
            return max(agree_indexes)
        return st["commitIndex"][i]

    def accept_pull_entries_request(self, st, m):
        """AcceptPullEntriesRequest — PullRaft.tla:460-488."""
        if not self._receivable(st, m, "PullEntriesRequest", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        index = d["mlastLogIndex"] + 1
        if (
            st["state"][i] != LEADER
            or not self._valid_pull_position(st, d)
            or index > len(st["log"][i])
        ):
            return None
        new_match_row = self._set(st["matchIndex"][i], j, d["mlastLogIndex"])
        new_ci = self._new_commit_index(st, i, new_match_row)
        ci = st["commitIndex"][i]
        committed_vals = {st["log"][i][ind - 1][1] for ind in range(ci + 1, new_ci + 1)}
        acked = tuple(
            (v in committed_vals) if st["acked"][v] is False else st["acked"][v]
            for v in range(self.V)
        )
        resp = rec(
            mtype="PullEntriesResponse",
            mterm=st["currentTerm"][i],
            msuccess=True,
            mentries=(st["log"][i][index - 1],),
            mcommitIndex=min(new_ci, index),
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(
            st,
            matchIndex=self._set(st["matchIndex"], i, new_match_row),
            commitIndex=self._set(st["commitIndex"], i, new_ci),
            acked=acked,
            messages=frozenset(msgs.items()),
        )

    def handle_success_pull_entries_response(self, st, m):
        """HandleSuccessPullEntriesResponse — PullRaft.tla:493-503."""
        if not self._receivable(st, m, "PullEntriesResponse", equal_term=True):
            return None
        d = dict(m)
        if not d["msuccess"]:
            return None
        i = d["mdest"]
        msgs = self._discard(self._msgs(st), m)
        return self._with(
            st,
            commitIndex=self._set(st["commitIndex"], i, d["mcommitIndex"]),
            log=self._set(st["log"], i, st["log"][i] + (d["mentries"][0],)),
            messages=frozenset(msgs.items()),
        )

    def handle_fail_pull_entries_response(self, st, m):
        """HandleFailPullEntriesResponse — PullRaft.tla:510-520: truncate to
        mlastCommonEntry.index (TruncateLog, PullRaft.tla:185-188)."""
        if not self._receivable(st, m, "PullEntriesResponse", equal_term=True):
            return None
        d = dict(m)
        if d["msuccess"]:
            return None
        i = d["mdest"]
        idx = d["mlastCommonEntry"][0]
        msgs = self._discard(self._msgs(st), m)
        return self._with(
            st,
            log=self._set(st["log"], i, st["log"][i][:idx]),
            messages=frozenset(msgs.items()),
        )

    # ---------- VIEW + SYMMETRY ----------

    @staticmethod
    def _ser_msgs(msgs) -> tuple:
        """Orderable form of the bag: None field values (Variant2's Nil
        mlastCommonEntry) become (-1, -1) so records compare."""

        def norm(m):
            return tuple(
                (k, (-1, -1) if v is None else v) for k, v in m
            )

        return tuple(sorted((norm(m), c) for m, c in msgs))

    def serialize_view(self, st) -> tuple:
        """PullRaft view INCLUDES acked (PullRaft.tla:123); Variant2's does
        not (PullRaftVariant2.tla:114)."""
        ack = {None: -1, False: 0, True: 1}
        base = (
            st["currentTerm"],
            st["state"],
            tuple(-1 if v is None else v for v in st["leader"]),
        )
        if self.variant2:
            base += (
                tuple(-1 if v is None else v for v in st["votedFor"]),
                tuple(
                    tuple((-1, -1) if e is None else e for e in row)
                    for row in st["votesLastEntry"]
                ),
            )
        base += (
            tuple(tuple(sorted(vs)) for vs in st["votesGranted"]),
            st["log"],
            st["commitIndex"],
            st["matchIndex"],
            self._ser_msgs(st["messages"]),
        )
        if not self.variant2:
            base += (tuple(ack[a] for a in st["acked"]),)
        return base

    def serialize_full(self, st) -> tuple:
        ack = {None: -1, False: 0, True: 1}
        return self.serialize_view(st) + (
            tuple(ack[a] for a in st["acked"]),
            st["electionCtr"],
            st["restartCtr"],
        )

    def permute(self, st, sigma) -> dict:
        """Apply a server permutation (old -> new index)."""
        S = self.S
        inv = [0] * S
        for old, new in enumerate(sigma):
            inv[new] = old

        def prow(t):
            return tuple(t[inv[k]] for k in range(S))

        def pmsg(m):
            d = dict(m)
            d["msource"] = sigma[d["msource"]]
            d["mdest"] = sigma[d["mdest"]]
            return rec(**d)

        extra = {}
        if self.variant2:
            extra["votedFor"] = tuple(
                None if v is None else sigma[v] for v in prow(st["votedFor"])
            )
            extra["votesLastEntry"] = tuple(
                prow(row) for row in prow(st["votesLastEntry"])
            )
        return self._with(
            st,
            currentTerm=prow(st["currentTerm"]),
            state=prow(st["state"]),
            leader=tuple(None if v is None else sigma[v] for v in prow(st["leader"])),
            votesGranted=tuple(
                frozenset(sigma[j] for j in vs) for vs in prow(st["votesGranted"])
            ),
            log=prow(st["log"]),
            commitIndex=prow(st["commitIndex"]),
            matchIndex=tuple(prow(row) for row in prow(st["matchIndex"])),
            messages=frozenset((pmsg(m), c) for m, c in st["messages"]),
            **extra,
        )

    def canon(self, st, symmetry: bool = True) -> tuple:
        if not symmetry:
            return self.serialize_view(st)
        return min(
            self.serialize_view(self.permute(st, list(sigma)))
            for sigma in itertools.permutations(range(self.S))
        )

    # ---------- invariants (PullRaft.tla:578-627) ----------

    def no_log_divergence(self, st) -> bool:
        for s1 in range(self.S):
            for s2 in range(self.S):
                if s1 == s2:
                    continue
                mci = min(st["commitIndex"][s1], st["commitIndex"][s2])
                for idx in range(1, mci + 1):
                    if st["log"][s1][idx - 1] != st["log"][s2][idx - 1]:
                        return False
        return True

    def leader_has_all_acked_values(self, st) -> bool:
        for v in range(self.V):
            if st["acked"][v] is not True:
                continue
            for i in range(self.S):
                if st["state"][i] != LEADER:
                    continue
                if any(
                    st["currentTerm"][l] > st["currentTerm"][i]
                    for l in range(self.S)
                    if l != i
                ):
                    continue
                if not any(e[1] == v for e in st["log"][i]):
                    return False
        return True

    def committed_entries_reach_majority(self, st) -> bool:
        leaders = [
            i
            for i in range(self.S)
            if st["state"][i] == LEADER and st["commitIndex"][i] > 0
        ]
        if not leaders:
            return True
        need = self.S // 2 + 1
        for i in leaders:
            ci = st["commitIndex"][i]
            entry = st["log"][i][ci - 1]
            n = sum(
                1
                for j in range(self.S)
                if len(st["log"][j]) >= ci and st["log"][j][ci - 1] == entry
            )
            if n >= need:
                return True
        return False

    INVARIANTS = {
        "NoLogDivergence": no_log_divergence,
        "LeaderHasAllAckedValues": leader_has_all_acked_values,
        "CommittedEntriesReachMajority": committed_entries_reach_majority,
        "TestInv": lambda self, st: True,
    }

    # ---------- BFS ----------

    def bfs(
        self,
        invariants: tuple[str, ...] = ("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry: bool = True,
        max_depth: int | None = None,
        max_states: int | None = None,
        time_budget_s: float | None = None,
    ) -> dict:
        import time

        t0 = time.perf_counter()
        init = self.init_state()
        seen = {self.canon(init, symmetry)}
        frontier = [init]
        total = 1
        distinct = 1
        depth_counts = [1]
        violation = None
        depth = 0
        while frontier and violation is None:
            if max_depth is not None and depth >= max_depth:
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                break
            next_frontier = []
            for st in frontier:
                for _label, s2 in self.successors(st):
                    total += 1
                    key = self.canon(s2, symmetry)
                    if key in seen:
                        continue
                    seen.add(key)
                    distinct += 1
                    for inv in invariants:
                        if not self.INVARIANTS[inv](self, s2):
                            violation = {
                                "invariant": inv,
                                "state": s2,
                                "depth": depth + 1,
                            }
                            break
                    next_frontier.append(s2)
                    if violation or (max_states and distinct >= max_states):
                        break
                if violation or (max_states and distinct >= max_states):
                    break
                if (
                    time_budget_s is not None
                    and (total & 0x3FF) < 8
                    and time.perf_counter() - t0 > time_budget_s
                ):
                    break
            frontier = next_frontier
            if frontier:
                depth_counts.append(len(frontier))
            depth += 1
        return {
            "distinct": distinct,
            "total": total,
            "depth_counts": depth_counts,
            "violation": violation,
        }
