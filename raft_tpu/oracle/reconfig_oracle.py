"""Independent pure-Python interpreter of
standard-raft/RaftWithReconfigAddRemove.tla.

Differential-testing ground truth for the TPU lowering in
models/reconfig_raft.py, written directly against the TLA+ text (reference
``/root/reference/specifications/standard-raft/RaftWithReconfigAddRemove.tla``,
1,083 lines) — NOT against the JAX kernels.

Key structural deltas vs. core Raft (see SURVEY.md §2.1):
  - thesis-style one-at-a-time add/remove reconfiguration: config commands
    live in the log (``AddServerCommand``/``RemoveServerCommand:66-69``),
    the current config is derived from the most recent one
    (``MostRecentReconfigEntry:252``, ``ConfigFor:265``);
  - pre-installed cluster ``Init`` (``:324-338``): a CHOOSE-selected member
    subset with a seeded ``InitClusterCommand`` first entry and an elected
    leader (lowest indices, matching deterministic CHOOSE);
  - snapshot catch-up for new members: ``SendSnapshot:862`` embeds the
    leader's WHOLE log in the message; ``nextIndex`` uses the sentinels
    ``PendingSnapshotRequest=-1``/``PendingSnapshotResponse=-2``
    (``:271-272``);
  - AppendEntries responses carry a result code
    (``Ok/StaleTerm/EntryMismatch/NeedSnapshot:75``);
  - member-aware quorums over ``config[i].members`` with leader
    self-exclusion when removed (``AdvanceCommitIndex:612-615``);
  - ``ResetWithSameIdentity:385`` is ENABLED in ``Next:965`` (drives the
    README's split-brain data-loss scenario);
  - ``IncludeThesisBug:92`` gates the
    ``LeaderHasCommittedEntriesInCurrentTerm`` fix (``:801-803,833-835``);
  - ``valueCtr`` bounds values per term (``ClientRequest:529``);
  - the stricter ``LogOk:650-667``: an empty AppendEntries must line up
    exactly with the end of the follower's log.

State dict format (shared with ReconfigRaftModel.decode/encode):
  config (per server: (id, frozenset members, committed)), currentTerm,
  state, votedFor, votesGranted, log, commitIndex, nextIndex (may hold the
  -1/-2 sentinels), matchIndex, pendingResponse, messages, acked,
  electionCtr, restartCtr, addReconfigCtr, removeReconfigCtr,
  valueCtr (tuple indexed by term-1).

Log entries are (command, term, value) with value:
  AppendCommand        -> int v
  InitClusterCommand   -> (id, frozenset members)
  AddServerCommand     -> (id, new_member, frozenset members)
  RemoveServerCommand  -> (id, old_member, frozenset members)
"""

from __future__ import annotations

from .config_oracle_base import ConfigOracleBase, last_term, rec

import itertools

FOLLOWER, CANDIDATE, LEADER, NOTMEMBER = range(4)

INIT_CMD = "InitClusterCommand"
APPEND_CMD = "AppendCommand"
ADD_CMD = "AddServerCommand"
REMOVE_CMD = "RemoveServerCommand"
CONFIG_CMDS = (INIT_CMD, ADD_CMD, REMOVE_CMD)

OK, STALE_TERM, ENTRY_MISMATCH, NEED_SNAPSHOT = (
    "Ok",
    "StaleTerm",
    "EntryMismatch",
    "NeedSnapshot",
)

PENDING_SNAP_REQUEST = -1  # RaftWithReconfigAddRemove.tla:271
PENDING_SNAP_RESPONSE = -2  # :272

NO_CONFIG = (0, frozenset(), False)  # NoConfig — :260-263






def is_config_command(entry) -> bool:
    """IsConfigCommand — RaftWithReconfigAddRemove.tla:241-244."""
    return entry[0] in CONFIG_CMDS


def most_recent_reconfig_entry(log) -> tuple[int, tuple]:
    """MostRecentReconfigEntry — :252-258 (1-based index, entry)."""
    best = 0
    for idx in range(1, len(log) + 1):
        if is_config_command(log[idx - 1]):
            best = idx
    assert best > 0, "log has no config command"
    return best, log[best - 1]


def config_for(index: int, entry: tuple, ci: int) -> tuple:
    """ConfigFor — :265-268: (id, members, committed)."""
    val = entry[2]
    # value is (id, members) for Init, (id, new/old, members) otherwise
    cfg_id = val[0]
    members = val[-1]
    return (cfg_id, members, ci >= index)


class ReconfigRaftOracle(ConfigOracleBase):
    def __init__(
        self,
        n_servers: int,
        n_values: int,
        init_cluster_size: int,
        max_elections: int,
        max_restarts: int,
        max_values_per_term: int,
        max_add_reconfigs: int,
        max_remove_reconfigs: int,
        min_cluster_size: int,
        max_cluster_size: int,
        include_thesis_bug: bool = False,
    ):
        self.S = n_servers
        self.V = n_values
        self.init_cluster_size = init_cluster_size
        self.max_elections = max_elections
        self.max_restarts = max_restarts
        self.max_values_per_term = max_values_per_term
        self.max_add = max_add_reconfigs
        self.max_remove = max_remove_reconfigs
        self.min_cluster = min_cluster_size
        self.max_cluster = max_cluster_size
        self.thesis_bug = include_thesis_bug
        self.max_term = 1 + max_elections

    MEMBERS_IDX = 1  # member-set slot of the config tuple
    _config_for = staticmethod(config_for)
    _mrre = staticmethod(most_recent_reconfig_entry)

    # ---------- state helpers ----------

    def init_state(self) -> dict:
        """Init — :324-338. CHOOSE of the member subset and leader is
        realized as lowest indices (deterministic; WLOG under SYMMETRY)."""
        S, V = self.S, self.V
        members = frozenset(range(self.init_cluster_size))
        leader = 0
        first = (INIT_CMD, 1, (1, members))
        return {
            "config": tuple(
                (1, members, True) if i in members else NO_CONFIG for i in range(S)
            ),
            "currentTerm": tuple(1 if i in members else 0 for i in range(S)),
            "state": tuple(
                LEADER if i == leader else FOLLOWER if i in members else NOTMEMBER
                for i in range(S)
            ),
            "votedFor": (None,) * S,
            "votesGranted": (frozenset(),) * S,
            "nextIndex": tuple(
                tuple(
                    2 if (i == leader and j in members) else 1 for j in range(S)
                )
                for i in range(S)
            ),
            "matchIndex": tuple(
                tuple(
                    1 if (i == leader and j in members) else 0 for j in range(S)
                )
                for i in range(S)
            ),
            "pendingResponse": ((False,) * S,) * S,
            "log": tuple((first,) if i in members else () for i in range(S)),
            "commitIndex": tuple(1 if i in members else 0 for i in range(S)),
            "messages": frozenset(),
            "acked": (None,) * V,
            "electionCtr": 0,
            "restartCtr": 0,
            "addReconfigCtr": 0,
            "removeReconfigCtr": 0,
            "valueCtr": (0,) * self.max_term,
        }

    # ---------- message-bag helpers (:175-223) ----------

    @classmethod
    def _send(cls, msgs, m):
        """Send — :192-196: empty AppendEntriesRequest is send-once."""
        d = dict(m)
        if d["mtype"] == "AppendEntriesRequest" and d["mentries"] == ():
            return cls._send_once(msgs, m)
        return cls._send_no_restriction(msgs, m)

    @staticmethod
    def _reply(msgs, response, request):
        """Reply — :217-223 (responses may duplicate here)."""
        out = dict(msgs)
        if out.get(request, 0) < 1:
            return None
        out[request] -= 1
        out[response] = out.get(response, 0) + 1
        return frozenset(out.items())

    def _has_pending_config(self, st, i) -> bool:
        """HasPendingConfigCommand — :248-249."""
        return st["config"][i][2] is False

    def _leader_has_committed_in_term(self, st, i) -> bool:
        """LeaderHasCommittedEntriesInCurrentTerm — :275-278."""
        return any(
            st["log"][i][idx][1] == st["currentTerm"][i]
            and st["commitIndex"][i] >= idx + 1
            for idx in range(len(st["log"][i]))
        )

    # ---------- actions (Next order, :943-965) ----------

    counter_keys = ("addReconfigCtr", "removeReconfigCtr")

    def _config_successors(self, st) -> list:
        out = []
        S = self.S
        for i in range(S):
            for a in range(S):
                s2 = self.append_add_server_command(st, i, a)
                if s2 is not None:
                    out.append((f"AppendAddServerCommandToLog({i},{a})", s2))
        for i in range(S):
            for r in range(S):
                s2 = self.append_remove_server_command(st, i, r)
                if s2 is not None:
                    out.append((f"AppendRemoveServerCommandToLog({i},{r})", s2))
        return out

    def _tail_successors(self, st) -> list:
        out = []
        for i in range(self.S):
            s2 = self.reset_with_same_identity(st, i)
            if s2 is not None:
                out.append((f"ResetWithSameIdentity({i})", s2))
        return out

    def become_leader(self, st, i):
        """BecomeLeader(i) — :505-518: quorum of config[i].members; the vote
        set must itself be a subset of the member set."""
        if st["state"][i] != CANDIDATE:
            return None
        members = st["config"][i][1]
        vg = st["votesGranted"][i]
        if not (vg <= members and 2 * len(vg) > len(members)):
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, LEADER),
            nextIndex=self._set(
                st["nextIndex"], i, (len(st["log"][i]) + 1,) * self.S
            ),
            matchIndex=self._set(st["matchIndex"], i, (0,) * self.S),
            pendingResponse=self._set(st["pendingResponse"], i, (False,) * self.S),
        )

    _mrre = staticmethod(most_recent_reconfig_entry)
    _config_for = staticmethod(config_for)

    def _commit_agree_ok(self, st, i, idx) -> bool:
        """Agree set must be a quorum of the member set (:617-618)."""
        members = st["config"][i][1]
        agree = {k for k in members if st["matchIndex"][i][k] >= idx}
        if i in members:
            agree |= {i}
        return agree <= members and 2 * len(agree) > len(members)

    def _committed_removal(self, log_i, idx, i) -> bool:
        """The leader leaves the cluster on committing its own removal
        (:633-640)."""
        return (log_i[idx - 1][0] == REMOVE_CMD
                and i not in log_i[idx - 1][2][-1])

    def append_add_server_command(self, st, i, add_member):
        """AppendAddServerCommandToLog — :795-824."""
        if st["state"][i] != LEADER:
            return None
        if st["addReconfigCtr"] >= self.max_add:
            return None
        cfg_id, members, _committed = st["config"][i]
        if len(members) >= self.max_cluster:
            return None
        if self._has_pending_config(st, i):
            return None
        if not self.thesis_bug and not self._leader_has_committed_in_term(st, i):
            return None
        if add_member in members:
            return None
        entry = (ADD_CMD, st["currentTerm"][i], (cfg_id + 1, add_member, members | {add_member}))
        new_log = st["log"][i] + (entry,)
        return self._with(
            st,
            log=self._set(st["log"], i, new_log),
            config=self._set(
                st["config"],
                i,
                config_for(len(new_log), entry, st["commitIndex"][i]),
            ),
            addReconfigCtr=st["addReconfigCtr"] + 1,
            nextIndex=self._set(
                st["nextIndex"],
                i,
                tuple(
                    PENDING_SNAP_REQUEST if s == add_member else st["nextIndex"][i][s]
                    for s in range(self.S)
                ),
            ),
        )

    def append_remove_server_command(self, st, i, remove_member):
        """AppendRemoveServerCommandToLog — :828-853."""
        if st["state"][i] != LEADER:
            return None
        if st["removeReconfigCtr"] >= self.max_remove:
            return None
        cfg_id, members, _committed = st["config"][i]
        if len(members) <= self.min_cluster:
            return None
        if not self.thesis_bug and not self._leader_has_committed_in_term(st, i):
            return None
        if self._has_pending_config(st, i):
            return None
        if remove_member not in members:
            return None
        entry = (
            REMOVE_CMD,
            st["currentTerm"][i],
            (cfg_id + 1, remove_member, members - {remove_member}),
        )
        new_log = st["log"][i] + (entry,)
        return self._with(
            st,
            log=self._set(st["log"], i, new_log),
            config=self._set(
                st["config"],
                i,
                config_for(len(new_log), entry, st["commitIndex"][i]),
            ),
            removeReconfigCtr=st["removeReconfigCtr"] + 1,
        )

    def reset_with_same_identity(self, st, i):
        """ResetWithSameIdentity(i) — :385-400 (enabled in Next:965); wipes
        a server the current leader confirms is outside the committed
        config."""
        if st["currentTerm"][i] <= 0:
            return None
        # IsSafeToWipe (:375-383); CHOOSE leader = lowest current leader
        leaders = [
            s
            for s in range(self.S)
            if st["state"][s] == LEADER
            and not any(
                st["currentTerm"][l] > st["currentTerm"][s]
                for l in range(self.S)
                if l != s
            )
        ]
        if not leaders:
            return None
        leader = leaders[0]
        if leader == i or i in st["config"][leader][1]:
            return None
        if not st["config"][leader][2]:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, NOTMEMBER),
            config=self._set(st["config"], i, NO_CONFIG),
            currentTerm=self._set(st["currentTerm"], i, 0),
            votedFor=self._set(st["votedFor"], i, None),
            votesGranted=self._set(st["votesGranted"], i, frozenset()),
            nextIndex=self._set(st["nextIndex"], i, (1,) * self.S),
            matchIndex=self._set(st["matchIndex"], i, (0,) * self.S),
            pendingResponse=self._set(st["pendingResponse"], i, (False,) * self.S),
            commitIndex=self._set(st["commitIndex"], i, 0),
            log=self._set(st["log"], i, ()),
        )

    # ---------- VIEW + SYMMETRY ----------

    def _ser_entry(self, e) -> tuple:
        cmd, term, val = e
        if cmd == APPEND_CMD:
            return (cmd, term, (val,))
        if cmd == INIT_CMD:
            return (cmd, term, (val[0], tuple(sorted(val[1]))))
        return (cmd, term, (val[0], val[1], tuple(sorted(val[2]))))

    def _ser_config_row(self, c) -> tuple:
        return (c[0], tuple(sorted(c[1])), c[2])

    def _perm_entry(self, e, sigma) -> tuple:
        cmd, term, val = e
        if cmd == APPEND_CMD:
            return e
        if cmd == INIT_CMD:
            return (cmd, term, (val[0], frozenset(sigma[x] for x in val[1])))
        return (
            cmd,
            term,
            (val[0], sigma[val[1]], frozenset(sigma[x] for x in val[2])),
        )

    def _perm_config_row(self, c, sigma) -> tuple:
        return (c[0], frozenset(sigma[x] for x in c[1]), c[2])

    # ---------- invariants (:1009-1078) ----------

    def _cfg_members_of(self, c) -> frozenset:
        return c[1]

    # no_log_divergence / leader_has_all_acked_values /
    # committed_entries_reach_majority: shared in ConfigOracleBase
    # (spec formulas :1017-1025/:1047-1063/:1067-1078)

    def max_one_reconfiguration_at_a_time(self, st) -> bool:
        """MaxOneReconfigurationAtATime — :1031-1039."""
        for i in range(self.S):
            if st["state"][i] != LEADER:
                continue
            uncommitted = [
                idx
                for idx in range(1, len(st["log"][i]) + 1)
                if is_config_command(st["log"][i][idx - 1])
                and st["commitIndex"][i] < idx
            ]
            if len(uncommitted) >= 2:
                return False
        return True

    INVARIANTS = {
        "NoLogDivergence": ConfigOracleBase.no_log_divergence,
        "MaxOneReconfigurationAtATime": max_one_reconfiguration_at_a_time,
        "LeaderHasAllAckedValues": ConfigOracleBase.leader_has_all_acked_values,
        "CommittedEntriesReachMajority":
            ConfigOracleBase.committed_entries_reach_majority,
        "TestInv": lambda self, st: True,
    }

