"""Shared machinery of the two reconfiguration-spec oracles.

``joint_oracle.py`` and ``reconfig_oracle.py`` interpret near-identical
TLA+ modules; their message-bag helpers, state-functional utilities and
the BFS driver were byte-identical copies (round-2 verdict Weak #8).
This base class holds them once. Everything where the two specs
genuinely differ (quorum rules, LogOk strictness, reconfig actions,
serialization of the differing entry shapes) stays in the subclasses —
oracles are the differential ground truth, so faithfulness to each
spec's text beats further deduplication.
"""

from __future__ import annotations

import itertools

# enums shared by both specs' oracles (identical values; the moved
# interpreters below resolve them from this module)
FOLLOWER, CANDIDATE, LEADER, NOTMEMBER = range(4)
APPEND_CMD = "AppendCommand"
OK, STALE_TERM, ENTRY_MISMATCH, NEED_SNAPSHOT = (
    "Ok",
    "StaleTerm",
    "EntryMismatch",
    "NeedSnapshot",
)
PENDING_SNAP_REQUEST = -1
PENDING_SNAP_RESPONSE = -2


def rec(**kw) -> tuple:
    return tuple(sorted(kw.items()))


def last_term(log) -> int:
    """LastTerm — JointConsensus :158 / AddRemove :173."""
    return log[-1][1] if log else 0



class ConfigOracleBase:

    @staticmethod
    def _discard(msgs, m):
        out = dict(msgs)
        assert out.get(m, 0) > 0
        out[m] -= 1
        return frozenset(out.items())

    def _set2(self, mat, i, j, val) -> tuple:
        return self._set(mat, i, self._set(mat[i], j, val))

    def _domain(self, st):
        return sorted((m for m, _c in st["messages"]), key=self._norm_rec)

    # ---------- message-bag + state-functional helpers ----------

    @staticmethod
    def _msgs(st) -> dict:
        return dict(st["messages"])

    @staticmethod
    def _send_multiple_once(msgs, ms):
        if any(m in msgs for m in ms):
            return None
        out = dict(msgs)
        for m in ms:
            out[m] = 1
        return frozenset(out.items())

    @staticmethod
    def _send_no_restriction(msgs, m):
        out = dict(msgs)
        out[m] = out.get(m, 0) + 1
        return frozenset(out.items())

    @staticmethod
    def _send_once(msgs, m):
        if m in msgs:
            return None
        out = dict(msgs)
        out[m] = 1
        return frozenset(out.items())

    def _ser_msgs(self, msgs) -> tuple:
        return tuple(sorted((self._norm_rec(m), c) for m, c in msgs))

    @staticmethod
    def _set(tup, i, val) -> tuple:
        return tup[:i] + (val,) + tup[i + 1 :]

    @staticmethod
    def _with(st, **updates) -> dict:
        out = dict(st)
        out.update(updates)
        return out

    def bfs(
        self,
        invariants: tuple[str, ...] = (
            "LeaderHasAllAckedValues",
            "NoLogDivergence",
            "MaxOneReconfigurationAtATime",
        ),
        symmetry: bool = True,
        max_depth: int | None = None,
        max_states: int | None = None,
        time_budget_s: float | None = None,
    ) -> dict:
        import time

        t0 = time.perf_counter()
        init = self.init_state()
        seen = {self.canon(init, symmetry)}
        frontier = [init]
        total = 1
        distinct = 1
        depth_counts = [1]
        violation = None
        depth = 0
        while frontier and violation is None:
            if max_states and distinct >= max_states:
                break  # hard cap (the inner breaks alone admitted one
                # extra state per depth level past the cap)
            if max_depth is not None and depth >= max_depth:
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                break
            next_frontier = []
            for st in frontier:
                for _label, s2 in self.successors(st):
                    total += 1
                    key = self.canon(s2, symmetry)
                    if key in seen:
                        continue
                    seen.add(key)
                    distinct += 1
                    for inv in invariants:
                        if not self.INVARIANTS[inv](self, s2):
                            violation = {
                                "invariant": inv,
                                "state": s2,
                                "depth": depth + 1,
                            }
                            break
                    next_frontier.append(s2)
                    if violation or (max_states and distinct >= max_states):
                        break
                if violation or (max_states and distinct >= max_states):
                    break
                if (
                    time_budget_s is not None
                    and (total & 0x3FF) < 8
                    and time.perf_counter() - t0 > time_budget_s
                ):
                    break
            frontier = next_frontier
            if frontier:
                depth_counts.append(len(frontier))
            depth += 1
        return {
            "distinct": distinct,
            "total": total,
            "depth_counts": depth_counts,
            "violation": violation,
        }

    # ---------- shared action interpreters ----------
    #
    # The two specs copy-inline this machinery almost verbatim (spec line
    # citations in the docstrings are the JointConsensus positions; the
    # AddRemove text is the same modulo ~20-line offsets). Subclasses
    # declare MEMBERS_IDX (the member-set slot of their config tuple) and
    # keep everything genuinely variant-specific: quorum rules, reconfig
    # appends, config projection (_config_for), serialization.

    MEMBERS_IDX: int
    # variant-dispatched config machinery (bound by the subclasses to
    # their module-level ConfigFor / MostRecentReconfigEntry)
    _config_for: staticmethod
    _mrre: staticmethod

    def _members(self, st, i):
        """The member set of server i's cached config."""
        return st["config"][i][self.MEMBERS_IDX]

    def restart(self, st, i):
        """Restart(i) — :362-374."""
        if st["restartCtr"] >= self.max_restarts:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, FOLLOWER),
            votesGranted=self._set(st["votesGranted"], i, frozenset()),
            nextIndex=self._set(st["nextIndex"], i, (1,) * self.S),
            matchIndex=self._set(st["matchIndex"], i, (0,) * self.S),
            pendingResponse=self._set(st["pendingResponse"], i, (False,) * self.S),
            commitIndex=self._set(st["commitIndex"], i, 0),
            restartCtr=st["restartCtr"] + 1,
        )

    def update_term(self, st, m):
        """UpdateTerm — :410-419."""
        d = dict(m)
        i = d["mdest"]
        if d["mterm"] <= st["currentTerm"][i]:
            return None
        return self._with(
            st,
            currentTerm=self._set(st["currentTerm"], i, d["mterm"]),
            state=self._set(st["state"], i, FOLLOWER),
            votedFor=self._set(st["votedFor"], i, None),
        )

    def request_vote(self, st, i):
        """RequestVote(i) — :431-450."""
        if st["electionCtr"] >= self.max_elections:
            return None
        if st["state"][i] not in (FOLLOWER, CANDIDATE):
            return None
        members = self._members(st, i)
        if i not in members:
            return None
        reqs = {
            rec(
                mtype="RequestVoteRequest",
                mterm=st["currentTerm"][i] + 1,
                mlastLogTerm=last_term(st["log"][i]),
                mlastLogIndex=len(st["log"][i]),
                msource=i,
                mdest=j,
            )
            for j in members
            if j != i
        }
        msgs = self._send_multiple_once(self._msgs(st), reqs)
        if msgs is None:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, CANDIDATE),
            currentTerm=self._set(st["currentTerm"], i, st["currentTerm"][i] + 1),
            votedFor=self._set(st["votedFor"], i, i),
            votesGranted=self._set(st["votesGranted"], i, frozenset({i})),
            electionCtr=st["electionCtr"] + 1,
            messages=msgs,
        )

    def handle_request_vote_request(self, st, m):
        """HandleRequestVoteRequest — :455-478."""
        if not self._receivable(st, m, "RequestVoteRequest", equal_term=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        log_ok = d["mlastLogTerm"] > last_term(st["log"][i]) or (
            d["mlastLogTerm"] == last_term(st["log"][i])
            and d["mlastLogIndex"] >= len(st["log"][i])
        )
        grant = (
            d["mterm"] == st["currentTerm"][i]
            and log_ok
            and st["votedFor"][i] in (None, j)
        )
        resp = rec(
            mtype="RequestVoteResponse",
            mterm=st["currentTerm"][i],
            mvoteGranted=grant,
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        extra = {}
        if grant:
            extra["votedFor"] = self._set(st["votedFor"], i, j)
        return self._with(st, messages=msgs, **extra)

    def handle_request_vote_response(self, st, m):
        """HandleRequestVoteResponse — :483-499."""
        if not self._receivable(st, m, "RequestVoteResponse", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != CANDIDATE:
            return None
        vg = st["votesGranted"][i] | {j} if d["mvoteGranted"] else st["votesGranted"][i]
        return self._with(
            st,
            votesGranted=self._set(st["votesGranted"], i, vg),
            messages=self._discard(self._msgs(st), m),
        )

    def client_request(self, st, i, v):
        """ClientRequest(i, v) — :535-550."""
        if st["state"][i] != LEADER or st["acked"][v] is not None:
            return None
        term = st["currentTerm"][i]
        if st["valueCtr"][term - 1] >= self.max_values_per_term:
            return None
        entry = (APPEND_CMD, term, v)
        return self._with(
            st,
            log=self._set(st["log"], i, st["log"][i] + (entry,)),
            acked=self._set(st["acked"], v, False),
            valueCtr=self._set(st["valueCtr"], term - 1, st["valueCtr"][term - 1] + 1),
        )

    def append_entries(self, st, i, j):
        """AppendEntries(i, j) — :556-582."""
        if st["state"][i] != LEADER:
            return None
        if j not in self._members(st, i):
            return None
        ni = st["nextIndex"][i][j]
        if ni < 0 or st["pendingResponse"][i][j]:
            return None
        log_i = st["log"][i]
        prev_idx = ni - 1
        prev_term = log_i[prev_idx - 1][1] if prev_idx > 0 else 0
        last_entry = min(len(log_i), ni)
        entries = tuple(log_i[ni - 1 : last_entry])
        msg = rec(
            mtype="AppendEntriesRequest",
            mterm=st["currentTerm"][i],
            mprevLogIndex=prev_idx,
            mprevLogTerm=prev_term,
            mentries=entries,
            mcommitIndex=min(st["commitIndex"][i], last_entry),
            msource=i,
            mdest=j,
        )
        msgs = self._send(self._msgs(st), msg)
        if msgs is None:
            return None
        return self._with(
            st,
            pendingResponse=self._set2(st["pendingResponse"], i, j, True),
            messages=msgs,
        )

    def _log_ok(self, st, i, d) -> bool:
        """LogOk — :660-677 (strict empty-entries arm)."""
        log_i = st["log"][i]
        if d["mentries"] != ():
            return (
                d["mprevLogIndex"] > 0
                and d["mprevLogIndex"] <= len(log_i)
                and d["mprevLogTerm"] == log_i[d["mprevLogIndex"] - 1][1]
            )
        return (
            d["mprevLogIndex"] == len(log_i)
            and d["mprevLogIndex"] > 0
            and d["mprevLogTerm"] == log_i[d["mprevLogIndex"] - 1][1]
        )

    def reject_append_entries_request(self, st, m):
        """RejectAppendEntriesRequest — :679-703."""
        if not self._receivable(st, m, "AppendEntriesRequest", equal_term=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if d["mterm"] < st["currentTerm"][i]:
            rc = STALE_TERM
        elif i not in self._members(st, i):
            rc = NEED_SNAPSHOT
        elif (
            d["mterm"] == st["currentTerm"][i]
            and st["state"][i] == FOLLOWER
            and not self._log_ok(st, i, d)
        ):
            rc = ENTRY_MISMATCH
        else:
            return None
        resp = rec(
            mtype="AppendEntriesResponse",
            mterm=st["currentTerm"][i],
            mresult=rc,
            mmatchIndex=0,
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def accept_append_entries_request(self, st, m):
        """AcceptAppendEntriesRequest — :726-763."""
        if not self._receivable(st, m, "AppendEntriesRequest", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] not in (FOLLOWER, CANDIDATE):
            return None
        if not self._log_ok(st, i, d):
            return None
        if i not in self._members(st, i):
            return None
        log_i = st["log"][i]
        index = d["mprevLogIndex"] + 1
        if d["mentries"] != () and len(log_i) == d["mprevLogIndex"]:
            new_log = log_i + (d["mentries"][0],)
        elif d["mentries"] != () and len(log_i) >= index:
            new_log = log_i[: d["mprevLogIndex"]] + (d["mentries"][0],)
        else:
            new_log = log_i
        cfg_idx, cfg_entry = self._mrre(new_log)
        new_config = self._config_for(cfg_idx, cfg_entry, d["mcommitIndex"])
        resp = rec(
            mtype="AppendEntriesResponse",
            mterm=st["currentTerm"][i],
            mresult=OK,
            mmatchIndex=d["mprevLogIndex"] + len(d["mentries"]),
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(
            st,
            config=self._set(st["config"], i, new_config),
            commitIndex=self._set(st["commitIndex"], i, d["mcommitIndex"]),
            state=self._set(
                st["state"], i, FOLLOWER if i in new_config[self.MEMBERS_IDX] else NOTMEMBER
            ),
            log=self._set(st["log"], i, new_log),
            messages=msgs,
        )

    def handle_append_entries_response(self, st, m):
        """HandleAppendEntriesResponse — :768-798."""
        if not self._receivable(st, m, "AppendEntriesResponse", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER:
            return None
        ni = st["nextIndex"]
        mi = st["matchIndex"]
        if d["mresult"] == OK:
            ni = self._set2(ni, i, j, d["mmatchIndex"] + 1)
            mi = self._set2(mi, i, j, d["mmatchIndex"])
        elif d["mresult"] == ENTRY_MISMATCH:
            ni = self._set2(ni, i, j, max(st["nextIndex"][i][j] - 1, 1))
        elif d["mresult"] == NEED_SNAPSHOT:
            ni = self._set2(ni, i, j, PENDING_SNAP_REQUEST)
        return self._with(
            st,
            nextIndex=ni,
            matchIndex=mi,
            pendingResponse=self._set2(st["pendingResponse"], i, j, False),
            messages=self._discard(self._msgs(st), m),
        )

    # ---------- reconfiguration (:827-944) ----------

    def send_snapshot(self, st, i, j):
        """SendSnapshot(i, j) — :885-901."""
        if st["state"][i] != LEADER:
            return None
        if j not in self._members(st, i):
            return None
        if st["nextIndex"][i][j] != PENDING_SNAP_REQUEST:
            return None
        msg = rec(
            mtype="SnapshotRequest",
            mterm=st["currentTerm"][i],
            mlog=st["log"][i],
            mcommitIndex=st["commitIndex"][i],
            mmembers=self._members(st, i),
            msource=i,
            mdest=j,
        )
        msgs = self._send(self._msgs(st), msg)
        if msgs is None:
            return None
        return self._with(
            st,
            nextIndex=self._set2(st["nextIndex"], i, j, PENDING_SNAP_RESPONSE),
            messages=msgs,
        )

    def handle_snapshot_request(self, st, m):
        """HandleSnapshotRequest — :905-927."""
        if not self._receivable(st, m, "SnapshotRequest", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != FOLLOWER:
            return None
        cfg_idx, cfg_entry = self._mrre(d["mlog"])
        resp = rec(
            mtype="SnapshotResponse",
            mterm=st["currentTerm"][i],
            msuccess=True,
            mmatchIndex=len(d["mlog"]),
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(
            st,
            commitIndex=self._set(st["commitIndex"], i, d["mcommitIndex"]),
            log=self._set(st["log"], i, d["mlog"]),
            config=self._set(
                st["config"], i, self._config_for(cfg_idx, cfg_entry, d["mcommitIndex"])
            ),
            messages=msgs,
        )

    def handle_snapshot_response(self, st, m):
        """HandleSnapshotResponse — :932-944."""
        if not self._receivable(st, m, "SnapshotResponse", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["nextIndex"][i][j] != PENDING_SNAP_RESPONSE:
            return None
        return self._with(
            st,
            nextIndex=self._set2(st["nextIndex"], i, j, d["mmatchIndex"] + 1),
            matchIndex=self._set2(st["matchIndex"], i, j, d["mmatchIndex"]),
            messages=self._discard(self._msgs(st), m),
        )

    # ---------- VIEW + SYMMETRY ----------

    # ---------------- shared Next enumeration (round-5 dedup) ------------
    # Variants supply only their reconfig arms (_config_successors) and
    # any arms between the snapshot handlers and the end of Next
    # (_tail_successors; AddRemove's ResetWithSameIdentity — the joint
    # spec comments it out of Next, :988).

    def _config_successors(self, st) -> list:
        raise NotImplementedError

    def _tail_successors(self, st) -> list:
        return []

    def successors(self, st) -> list:
        out = []
        S, V = self.S, self.V
        for i in range(S):
            s2 = self.restart(st, i)
            if s2 is not None:
                out.append((f"Restart({i})", s2))
        for m in self._domain(st):
            s2 = self.update_term(st, m)
            if s2 is not None:
                out.append(("UpdateTerm", s2))
        for i in range(S):
            s2 = self.request_vote(st, i)
            if s2 is not None:
                out.append((f"RequestVote({i})", s2))
        for i in range(S):
            s2 = self.become_leader(st, i)
            if s2 is not None:
                out.append((f"BecomeLeader({i})", s2))
        for m in self._domain(st):
            s2 = self.handle_request_vote_request(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteRequest", s2))
        for m in self._domain(st):
            s2 = self.handle_request_vote_response(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteResponse", s2))
        for i in range(S):
            for v in range(V):
                s2 = self.client_request(st, i, v)
                if s2 is not None:
                    out.append((f"ClientRequest({i},{v})", s2))
        for i in range(S):
            s2 = self.advance_commit_index(st, i)
            if s2 is not None:
                out.append((f"AdvanceCommitIndex({i})", s2))
        for i in range(S):
            for j in range(S):
                if i != j:
                    s2 = self.append_entries(st, i, j)
                    if s2 is not None:
                        out.append((f"AppendEntries({i},{j})", s2))
        for m in self._domain(st):
            s2 = self.reject_append_entries_request(st, m)
            if s2 is not None:
                out.append(("RejectAppendEntriesRequest", s2))
        for m in self._domain(st):
            s2 = self.accept_append_entries_request(st, m)
            if s2 is not None:
                out.append(("AcceptAppendEntriesRequest", s2))
        for m in self._domain(st):
            s2 = self.handle_append_entries_response(st, m)
            if s2 is not None:
                out.append(("HandleAppendEntriesResponse", s2))
        out += self._config_successors(st)
        for i in range(S):
            for j in range(S):
                if i != j:
                    s2 = self.send_snapshot(st, i, j)
                    if s2 is not None:
                        out.append((f"SendSnapshot({i},{j})", s2))
        for m in self._domain(st):
            s2 = self.handle_snapshot_request(st, m)
            if s2 is not None:
                out.append(("HandleSnapshotRequest", s2))
        for m in self._domain(st):
            s2 = self.handle_snapshot_response(st, m)
            if s2 is not None:
                out.append(("HandleSnapshotResponse", s2))
        out += self._tail_successors(st)
        return out

    # ------------- shared VIEW/SYMMETRY serialization (round-5) -----------
    # Variant hooks: per-entry and per-config-row serialization and
    # permutation, plus the spec's extra bounding counters.

    counter_keys: tuple = ()

    def _ser_entry(self, e) -> tuple:
        raise NotImplementedError

    def _ser_config_row(self, c) -> tuple:
        raise NotImplementedError

    def _perm_entry(self, e, sigma) -> tuple:
        raise NotImplementedError

    def _perm_config_row(self, c, sigma) -> tuple:
        raise NotImplementedError

    def _ser_log(self, log) -> tuple:
        return tuple(tuple(self._ser_entry(e) for e in lg) for lg in log)

    def serialize_view(self, st) -> tuple:
        """The cfg VIEW: aux vars excluded (joint :144, add/remove :159)."""
        return (
            tuple(self._ser_config_row(c) for c in st["config"]),
            st["currentTerm"],
            st["state"],
            tuple(-1 if v is None else v for v in st["votedFor"]),
            tuple(tuple(sorted(vs)) for vs in st["votesGranted"]),
            st["nextIndex"],
            st["matchIndex"],
            st["pendingResponse"],
            self._ser_log(st["log"]),
            st["commitIndex"],
            self._ser_msgs(st["messages"]),
        )

    def serialize_full(self, st) -> tuple:
        ack = {None: -1, False: 0, True: 1}
        return (
            self.serialize_view(st)
            + (
                tuple(ack[a] for a in st["acked"]),
                st["electionCtr"],
                st["restartCtr"],
            )
            + tuple(st[k] for k in self.counter_keys)
            + (st["valueCtr"],)
        )

    def permute(self, st, sigma) -> dict:
        """Apply a server permutation (old -> new index)."""
        S = self.S
        inv = [0] * S
        for old, new in enumerate(sigma):
            inv[new] = old

        def prow(t):
            return tuple(t[inv[k]] for k in range(S))

        def pmsg(m):
            d = dict(m)
            d["msource"] = sigma[d["msource"]]
            d["mdest"] = sigma[d["mdest"]]
            if "mentries" in d:
                d["mentries"] = tuple(
                    self._perm_entry(e, sigma) for e in d["mentries"])
            if "mlog" in d:
                d["mlog"] = tuple(
                    self._perm_entry(e, sigma) for e in d["mlog"])
            if "mmembers" in d:
                d["mmembers"] = frozenset(sigma[x] for x in d["mmembers"])
            return rec(**d)

        return self._with(
            st,
            config=tuple(
                self._perm_config_row(c, sigma) for c in prow(st["config"])
            ),
            currentTerm=prow(st["currentTerm"]),
            state=prow(st["state"]),
            votedFor=tuple(
                None if v is None else sigma[v] for v in prow(st["votedFor"])
            ),
            votesGranted=tuple(
                frozenset(sigma[j] for j in vs) for vs in prow(st["votesGranted"])
            ),
            nextIndex=tuple(prow(row) for row in prow(st["nextIndex"])),
            matchIndex=tuple(prow(row) for row in prow(st["matchIndex"])),
            pendingResponse=tuple(prow(row) for row in prow(st["pendingResponse"])),
            log=tuple(
                tuple(self._perm_entry(e, sigma) for e in lg)
                for lg in prow(st["log"])
            ),
            commitIndex=prow(st["commitIndex"]),
            messages=frozenset((pmsg(m), c) for m, c in st["messages"]),
        )

    def canon(self, st, symmetry: bool = True) -> tuple:
        if not symmetry:
            return self.serialize_view(st)
        return min(
            self.serialize_view(self.permute(st, list(sigma)))
            for sigma in itertools.permutations(range(self.S))
        )

    # -------- shared invariants (round-5 dedup; joint :1058-1140,
    # add/remove :1009-1078 — identical up to the config-row members
    # accessor; MaxOneReconfigurationAtATime stays variant-specific) ----

    def _cfg_members_of(self, c) -> frozenset:
        raise NotImplementedError  # members set inside a config row

    def no_log_divergence(self, st) -> bool:
        """Full-entry equality below the joint commitIndex."""
        for s1 in range(self.S):
            for s2 in range(self.S):
                if s1 == s2:
                    continue
                ci = min(st["commitIndex"][s1], st["commitIndex"][s2])
                for idx in range(1, ci + 1):
                    if st["log"][s1][idx - 1] != st["log"][s2][idx - 1]:
                        return False
        return True

    def leader_has_all_acked_values(self, st) -> bool:
        """Only AppendCommand entries can match a client value."""
        for v in range(self.V):
            if st["acked"][v] is not True:
                continue
            for i in range(self.S):
                if st["state"][i] != LEADER:
                    continue
                if any(
                    st["currentTerm"][l] > st["currentTerm"][i]
                    for l in range(self.S)
                    if l != i
                ):
                    continue
                if not any(
                    e[0] == APPEND_CMD and e[2] == v for e in st["log"][i]
                ):
                    return False
        return True

    def committed_entries_reach_majority(self, st) -> bool:
        """Quorum drawn from config[i].members and must contain i."""
        leaders = [
            i
            for i in range(self.S)
            if st["state"][i] == LEADER and st["commitIndex"][i] > 0
        ]
        if not leaders:
            return True
        for i in leaders:
            members = self._cfg_members_of(st["config"][i])
            if i not in members:
                continue
            ci = st["commitIndex"][i]
            if len(st["log"][i]) < ci:
                continue
            entry = st["log"][i][ci - 1]
            agree = {
                j
                for j in members
                if len(st["log"][j]) >= ci and st["log"][j][ci - 1] == entry
            }
            if i in agree and len(agree) >= len(members) // 2 + 1:
                return True
        return False

    # ------ shared AdvanceCommitIndex skeleton (round-5 dedup; joint
    # :613-653 dual-quorum, add/remove :605-642 member quorum) ---------

    def _commit_agree_ok(self, st, i, idx) -> bool:
        raise NotImplementedError  # variant quorum rule at log index idx

    def _committed_removal(self, log_i, idx, i) -> bool:
        raise NotImplementedError  # did committing idx remove server i?

    _mrre = None  # staticmethod(most_recent_reconfig_entry) per variant
    _config_for = None  # staticmethod(config_for) per variant

    def advance_commit_index(self, st, i):
        if st["state"][i] != LEADER:
            return None
        log_i = st["log"][i]
        best = 0
        for idx in range(1, len(log_i) + 1):
            if self._commit_agree_ok(st, i, idx):
                best = idx
        new_ci = (
            best
            if best > 0 and log_i[best - 1][1] == st["currentTerm"][i]
            else st["commitIndex"][i]
        )
        if st["commitIndex"][i] >= new_ci:
            return None
        acked = list(st["acked"])
        for idx in range(st["commitIndex"][i] + 1, new_ci + 1):
            cmd, _t, val = log_i[idx - 1]
            if cmd == APPEND_CMD and st["acked"][val] is False:
                acked[val] = True
        cfg_idx, cfg_entry = type(self)._mrre(log_i)
        new_config = type(self)._config_for(cfg_idx, cfg_entry, new_ci)
        removed = any(
            self._committed_removal(log_i, idx, i)
            for idx in range(st["commitIndex"][i] + 1, new_ci + 1)
        )
        upd = dict(
            acked=tuple(acked),
            config=self._set(st["config"], i, new_config),
        )
        if removed:
            upd.update(
                state=self._set(st["state"], i, NOTMEMBER),
                votesGranted=self._set(st["votesGranted"], i, frozenset()),
                nextIndex=self._set(st["nextIndex"], i, (1,) * self.S),
                matchIndex=self._set(st["matchIndex"], i, (0,) * self.S),
                commitIndex=self._set(st["commitIndex"], i, 0),
            )
        else:
            upd["commitIndex"] = self._set(st["commitIndex"], i, new_ci)
        return self._with(st, **upd)

    def _receivable(self, st, m, mtype: str, equal_term: bool) -> bool:
        """ReceivableMessage — :212-218."""
        d = dict(m)
        msgs = self._msgs(st)
        if msgs.get(m, 0) < 1 or d["mtype"] != mtype:
            return False
        if equal_term:
            return d["mterm"] == st["currentTerm"][d["mdest"]]
        return d["mterm"] <= st["currentTerm"][d["mdest"]]

    @staticmethod
    def _norm_rec(m) -> tuple:
        def norm_val(v):
            if v is None:
                return (0, 0)
            if isinstance(v, bool):
                return (1, int(v))
            if isinstance(v, int):
                return (2, v)
            if isinstance(v, str):
                return (3, v)
            if isinstance(v, frozenset):
                return (4, tuple(sorted(v)))
            if isinstance(v, tuple):
                return (5, tuple(norm_val(x) for x in v))
            raise TypeError(v)

        return tuple((k, norm_val(v)) for k, v in m)

    # ---------- config helpers ----------

