"""Shared machinery of the two reconfiguration-spec oracles.

``joint_oracle.py`` and ``reconfig_oracle.py`` interpret near-identical
TLA+ modules; their message-bag helpers, state-functional utilities and
the BFS driver were byte-identical copies (round-2 verdict Weak #8).
This base class holds them once. Everything where the two specs
genuinely differ (quorum rules, LogOk strictness, reconfig actions,
serialization of the differing entry shapes) stays in the subclasses —
oracles are the differential ground truth, so faithfulness to each
spec's text beats further deduplication.
"""

from __future__ import annotations

# enums shared by both specs' oracles (identical values; the moved
# interpreters below resolve them from this module)
FOLLOWER, CANDIDATE, LEADER, NOTMEMBER = range(4)
APPEND_CMD = "AppendCommand"
OK, STALE_TERM, ENTRY_MISMATCH, NEED_SNAPSHOT = (
    "Ok",
    "StaleTerm",
    "EntryMismatch",
    "NeedSnapshot",
)
PENDING_SNAP_REQUEST = -1
PENDING_SNAP_RESPONSE = -2


def rec(**kw) -> tuple:
    return tuple(sorted(kw.items()))


def last_term(log) -> int:
    """LastTerm — JointConsensus :158 / AddRemove :173."""
    return log[-1][1] if log else 0



class ConfigOracleBase:

    @staticmethod
    def _discard(msgs, m):
        out = dict(msgs)
        assert out.get(m, 0) > 0
        out[m] -= 1
        return frozenset(out.items())

    def _set2(self, mat, i, j, val) -> tuple:
        return self._set(mat, i, self._set(mat[i], j, val))

    def _domain(self, st):
        return sorted((m for m, _c in st["messages"]), key=self._norm_rec)

    # ---------- message-bag + state-functional helpers ----------

    @staticmethod
    def _msgs(st) -> dict:
        return dict(st["messages"])

    @staticmethod
    def _send_multiple_once(msgs, ms):
        if any(m in msgs for m in ms):
            return None
        out = dict(msgs)
        for m in ms:
            out[m] = 1
        return frozenset(out.items())

    @staticmethod
    def _send_no_restriction(msgs, m):
        out = dict(msgs)
        out[m] = out.get(m, 0) + 1
        return frozenset(out.items())

    @staticmethod
    def _send_once(msgs, m):
        if m in msgs:
            return None
        out = dict(msgs)
        out[m] = 1
        return frozenset(out.items())

    def _ser_msgs(self, msgs) -> tuple:
        return tuple(sorted((self._norm_rec(m), c) for m, c in msgs))

    @staticmethod
    def _set(tup, i, val) -> tuple:
        return tup[:i] + (val,) + tup[i + 1 :]

    @staticmethod
    def _with(st, **updates) -> dict:
        out = dict(st)
        out.update(updates)
        return out

    def bfs(
        self,
        invariants: tuple[str, ...] = (
            "LeaderHasAllAckedValues",
            "NoLogDivergence",
            "MaxOneReconfigurationAtATime",
        ),
        symmetry: bool = True,
        max_depth: int | None = None,
        max_states: int | None = None,
        time_budget_s: float | None = None,
    ) -> dict:
        import time

        t0 = time.perf_counter()
        init = self.init_state()
        seen = {self.canon(init, symmetry)}
        frontier = [init]
        total = 1
        distinct = 1
        depth_counts = [1]
        violation = None
        depth = 0
        while frontier and violation is None:
            if max_states and distinct >= max_states:
                break  # hard cap (the inner breaks alone admitted one
                # extra state per depth level past the cap)
            if max_depth is not None and depth >= max_depth:
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                break
            next_frontier = []
            for st in frontier:
                for _label, s2 in self.successors(st):
                    total += 1
                    key = self.canon(s2, symmetry)
                    if key in seen:
                        continue
                    seen.add(key)
                    distinct += 1
                    for inv in invariants:
                        if not self.INVARIANTS[inv](self, s2):
                            violation = {
                                "invariant": inv,
                                "state": s2,
                                "depth": depth + 1,
                            }
                            break
                    next_frontier.append(s2)
                    if violation or (max_states and distinct >= max_states):
                        break
                if violation or (max_states and distinct >= max_states):
                    break
                if (
                    time_budget_s is not None
                    and (total & 0x3FF) < 8
                    and time.perf_counter() - t0 > time_budget_s
                ):
                    break
            frontier = next_frontier
            if frontier:
                depth_counts.append(len(frontier))
            depth += 1
        return {
            "distinct": distinct,
            "total": total,
            "depth_counts": depth_counts,
            "violation": violation,
        }

    # ---------- shared action interpreters ----------
    #
    # The two specs copy-inline this machinery almost verbatim (spec line
    # citations in the docstrings are the JointConsensus positions; the
    # AddRemove text is the same modulo ~20-line offsets). Subclasses
    # declare MEMBERS_IDX (the member-set slot of their config tuple) and
    # keep everything genuinely variant-specific: quorum rules, reconfig
    # appends, config projection (_config_for), serialization.

    MEMBERS_IDX: int
    # variant-dispatched config machinery (bound by the subclasses to
    # their module-level ConfigFor / MostRecentReconfigEntry)
    _config_for: staticmethod
    _mrre: staticmethod

    def _members(self, st, i):
        """The member set of server i's cached config."""
        return st["config"][i][self.MEMBERS_IDX]

    def restart(self, st, i):
        """Restart(i) — :362-374."""
        if st["restartCtr"] >= self.max_restarts:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, FOLLOWER),
            votesGranted=self._set(st["votesGranted"], i, frozenset()),
            nextIndex=self._set(st["nextIndex"], i, (1,) * self.S),
            matchIndex=self._set(st["matchIndex"], i, (0,) * self.S),
            pendingResponse=self._set(st["pendingResponse"], i, (False,) * self.S),
            commitIndex=self._set(st["commitIndex"], i, 0),
            restartCtr=st["restartCtr"] + 1,
        )

    def update_term(self, st, m):
        """UpdateTerm — :410-419."""
        d = dict(m)
        i = d["mdest"]
        if d["mterm"] <= st["currentTerm"][i]:
            return None
        return self._with(
            st,
            currentTerm=self._set(st["currentTerm"], i, d["mterm"]),
            state=self._set(st["state"], i, FOLLOWER),
            votedFor=self._set(st["votedFor"], i, None),
        )

    def request_vote(self, st, i):
        """RequestVote(i) — :431-450."""
        if st["electionCtr"] >= self.max_elections:
            return None
        if st["state"][i] not in (FOLLOWER, CANDIDATE):
            return None
        members = self._members(st, i)
        if i not in members:
            return None
        reqs = {
            rec(
                mtype="RequestVoteRequest",
                mterm=st["currentTerm"][i] + 1,
                mlastLogTerm=last_term(st["log"][i]),
                mlastLogIndex=len(st["log"][i]),
                msource=i,
                mdest=j,
            )
            for j in members
            if j != i
        }
        msgs = self._send_multiple_once(self._msgs(st), reqs)
        if msgs is None:
            return None
        return self._with(
            st,
            state=self._set(st["state"], i, CANDIDATE),
            currentTerm=self._set(st["currentTerm"], i, st["currentTerm"][i] + 1),
            votedFor=self._set(st["votedFor"], i, i),
            votesGranted=self._set(st["votesGranted"], i, frozenset({i})),
            electionCtr=st["electionCtr"] + 1,
            messages=msgs,
        )

    def handle_request_vote_request(self, st, m):
        """HandleRequestVoteRequest — :455-478."""
        if not self._receivable(st, m, "RequestVoteRequest", equal_term=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        log_ok = d["mlastLogTerm"] > last_term(st["log"][i]) or (
            d["mlastLogTerm"] == last_term(st["log"][i])
            and d["mlastLogIndex"] >= len(st["log"][i])
        )
        grant = (
            d["mterm"] == st["currentTerm"][i]
            and log_ok
            and st["votedFor"][i] in (None, j)
        )
        resp = rec(
            mtype="RequestVoteResponse",
            mterm=st["currentTerm"][i],
            mvoteGranted=grant,
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        extra = {}
        if grant:
            extra["votedFor"] = self._set(st["votedFor"], i, j)
        return self._with(st, messages=msgs, **extra)

    def handle_request_vote_response(self, st, m):
        """HandleRequestVoteResponse — :483-499."""
        if not self._receivable(st, m, "RequestVoteResponse", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != CANDIDATE:
            return None
        vg = st["votesGranted"][i] | {j} if d["mvoteGranted"] else st["votesGranted"][i]
        return self._with(
            st,
            votesGranted=self._set(st["votesGranted"], i, vg),
            messages=self._discard(self._msgs(st), m),
        )

    def client_request(self, st, i, v):
        """ClientRequest(i, v) — :535-550."""
        if st["state"][i] != LEADER or st["acked"][v] is not None:
            return None
        term = st["currentTerm"][i]
        if st["valueCtr"][term - 1] >= self.max_values_per_term:
            return None
        entry = (APPEND_CMD, term, v)
        return self._with(
            st,
            log=self._set(st["log"], i, st["log"][i] + (entry,)),
            acked=self._set(st["acked"], v, False),
            valueCtr=self._set(st["valueCtr"], term - 1, st["valueCtr"][term - 1] + 1),
        )

    def append_entries(self, st, i, j):
        """AppendEntries(i, j) — :556-582."""
        if st["state"][i] != LEADER:
            return None
        if j not in self._members(st, i):
            return None
        ni = st["nextIndex"][i][j]
        if ni < 0 or st["pendingResponse"][i][j]:
            return None
        log_i = st["log"][i]
        prev_idx = ni - 1
        prev_term = log_i[prev_idx - 1][1] if prev_idx > 0 else 0
        last_entry = min(len(log_i), ni)
        entries = tuple(log_i[ni - 1 : last_entry])
        msg = rec(
            mtype="AppendEntriesRequest",
            mterm=st["currentTerm"][i],
            mprevLogIndex=prev_idx,
            mprevLogTerm=prev_term,
            mentries=entries,
            mcommitIndex=min(st["commitIndex"][i], last_entry),
            msource=i,
            mdest=j,
        )
        msgs = self._send(self._msgs(st), msg)
        if msgs is None:
            return None
        return self._with(
            st,
            pendingResponse=self._set2(st["pendingResponse"], i, j, True),
            messages=msgs,
        )

    def _log_ok(self, st, i, d) -> bool:
        """LogOk — :660-677 (strict empty-entries arm)."""
        log_i = st["log"][i]
        if d["mentries"] != ():
            return (
                d["mprevLogIndex"] > 0
                and d["mprevLogIndex"] <= len(log_i)
                and d["mprevLogTerm"] == log_i[d["mprevLogIndex"] - 1][1]
            )
        return (
            d["mprevLogIndex"] == len(log_i)
            and d["mprevLogIndex"] > 0
            and d["mprevLogTerm"] == log_i[d["mprevLogIndex"] - 1][1]
        )

    def reject_append_entries_request(self, st, m):
        """RejectAppendEntriesRequest — :679-703."""
        if not self._receivable(st, m, "AppendEntriesRequest", equal_term=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if d["mterm"] < st["currentTerm"][i]:
            rc = STALE_TERM
        elif i not in self._members(st, i):
            rc = NEED_SNAPSHOT
        elif (
            d["mterm"] == st["currentTerm"][i]
            and st["state"][i] == FOLLOWER
            and not self._log_ok(st, i, d)
        ):
            rc = ENTRY_MISMATCH
        else:
            return None
        resp = rec(
            mtype="AppendEntriesResponse",
            mterm=st["currentTerm"][i],
            mresult=rc,
            mmatchIndex=0,
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def accept_append_entries_request(self, st, m):
        """AcceptAppendEntriesRequest — :726-763."""
        if not self._receivable(st, m, "AppendEntriesRequest", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] not in (FOLLOWER, CANDIDATE):
            return None
        if not self._log_ok(st, i, d):
            return None
        if i not in self._members(st, i):
            return None
        log_i = st["log"][i]
        index = d["mprevLogIndex"] + 1
        if d["mentries"] != () and len(log_i) == d["mprevLogIndex"]:
            new_log = log_i + (d["mentries"][0],)
        elif d["mentries"] != () and len(log_i) >= index:
            new_log = log_i[: d["mprevLogIndex"]] + (d["mentries"][0],)
        else:
            new_log = log_i
        cfg_idx, cfg_entry = self._mrre(new_log)
        new_config = self._config_for(cfg_idx, cfg_entry, d["mcommitIndex"])
        resp = rec(
            mtype="AppendEntriesResponse",
            mterm=st["currentTerm"][i],
            mresult=OK,
            mmatchIndex=d["mprevLogIndex"] + len(d["mentries"]),
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(
            st,
            config=self._set(st["config"], i, new_config),
            commitIndex=self._set(st["commitIndex"], i, d["mcommitIndex"]),
            state=self._set(
                st["state"], i, FOLLOWER if i in new_config[self.MEMBERS_IDX] else NOTMEMBER
            ),
            log=self._set(st["log"], i, new_log),
            messages=msgs,
        )

    def handle_append_entries_response(self, st, m):
        """HandleAppendEntriesResponse — :768-798."""
        if not self._receivable(st, m, "AppendEntriesResponse", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER:
            return None
        ni = st["nextIndex"]
        mi = st["matchIndex"]
        if d["mresult"] == OK:
            ni = self._set2(ni, i, j, d["mmatchIndex"] + 1)
            mi = self._set2(mi, i, j, d["mmatchIndex"])
        elif d["mresult"] == ENTRY_MISMATCH:
            ni = self._set2(ni, i, j, max(st["nextIndex"][i][j] - 1, 1))
        elif d["mresult"] == NEED_SNAPSHOT:
            ni = self._set2(ni, i, j, PENDING_SNAP_REQUEST)
        return self._with(
            st,
            nextIndex=ni,
            matchIndex=mi,
            pendingResponse=self._set2(st["pendingResponse"], i, j, False),
            messages=self._discard(self._msgs(st), m),
        )

    # ---------- reconfiguration (:827-944) ----------

    def send_snapshot(self, st, i, j):
        """SendSnapshot(i, j) — :885-901."""
        if st["state"][i] != LEADER:
            return None
        if j not in self._members(st, i):
            return None
        if st["nextIndex"][i][j] != PENDING_SNAP_REQUEST:
            return None
        msg = rec(
            mtype="SnapshotRequest",
            mterm=st["currentTerm"][i],
            mlog=st["log"][i],
            mcommitIndex=st["commitIndex"][i],
            mmembers=self._members(st, i),
            msource=i,
            mdest=j,
        )
        msgs = self._send(self._msgs(st), msg)
        if msgs is None:
            return None
        return self._with(
            st,
            nextIndex=self._set2(st["nextIndex"], i, j, PENDING_SNAP_RESPONSE),
            messages=msgs,
        )

    def handle_snapshot_request(self, st, m):
        """HandleSnapshotRequest — :905-927."""
        if not self._receivable(st, m, "SnapshotRequest", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != FOLLOWER:
            return None
        cfg_idx, cfg_entry = self._mrre(d["mlog"])
        resp = rec(
            mtype="SnapshotResponse",
            mterm=st["currentTerm"][i],
            msuccess=True,
            mmatchIndex=len(d["mlog"]),
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(
            st,
            commitIndex=self._set(st["commitIndex"], i, d["mcommitIndex"]),
            log=self._set(st["log"], i, d["mlog"]),
            config=self._set(
                st["config"], i, self._config_for(cfg_idx, cfg_entry, d["mcommitIndex"])
            ),
            messages=msgs,
        )

    def handle_snapshot_response(self, st, m):
        """HandleSnapshotResponse — :932-944."""
        if not self._receivable(st, m, "SnapshotResponse", equal_term=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["nextIndex"][i][j] != PENDING_SNAP_RESPONSE:
            return None
        return self._with(
            st,
            nextIndex=self._set2(st["nextIndex"], i, j, d["mmatchIndex"] + 1),
            matchIndex=self._set2(st["matchIndex"], i, j, d["mmatchIndex"]),
            messages=self._discard(self._msgs(st), m),
        )

    # ---------- VIEW + SYMMETRY ----------

    def _receivable(self, st, m, mtype: str, equal_term: bool) -> bool:
        """ReceivableMessage — :212-218."""
        d = dict(m)
        msgs = self._msgs(st)
        if msgs.get(m, 0) < 1 or d["mtype"] != mtype:
            return False
        if equal_term:
            return d["mterm"] == st["currentTerm"][d["mdest"]]
        return d["mterm"] <= st["currentTerm"][d["mdest"]]

    @staticmethod
    def _norm_rec(m) -> tuple:
        def norm_val(v):
            if v is None:
                return (0, 0)
            if isinstance(v, bool):
                return (1, int(v))
            if isinstance(v, int):
                return (2, v)
            if isinstance(v, str):
                return (3, v)
            if isinstance(v, frozenset):
                return (4, tuple(sorted(v)))
            if isinstance(v, tuple):
                return (5, tuple(norm_val(x) for x in v))
            raise TypeError(v)

        return tuple((k, norm_val(v)) for k, v in m)

    # ---------- config helpers ----------

