"""Shared machinery of the two reconfiguration-spec oracles.

``joint_oracle.py`` and ``reconfig_oracle.py`` interpret near-identical
TLA+ modules; their message-bag helpers, state-functional utilities and
the BFS driver were byte-identical copies (round-2 verdict Weak #8).
This base class holds them once. Everything where the two specs
genuinely differ (quorum rules, LogOk strictness, reconfig actions,
serialization of the differing entry shapes) stays in the subclasses —
oracles are the differential ground truth, so faithfulness to each
spec's text beats further deduplication.
"""

from __future__ import annotations


class ConfigOracleBase:

    @staticmethod
    def _discard(msgs, m):
        out = dict(msgs)
        assert out.get(m, 0) > 0
        out[m] -= 1
        return frozenset(out.items())

    def _set2(self, mat, i, j, val) -> tuple:
        return self._set(mat, i, self._set(mat[i], j, val))

    def _domain(self, st):
        return sorted((m for m, _c in st["messages"]), key=self._norm_rec)

    # ---------- message-bag + state-functional helpers ----------

    @staticmethod
    def _msgs(st) -> dict:
        return dict(st["messages"])

    @staticmethod
    def _send_multiple_once(msgs, ms):
        if any(m in msgs for m in ms):
            return None
        out = dict(msgs)
        for m in ms:
            out[m] = 1
        return frozenset(out.items())

    @staticmethod
    def _send_no_restriction(msgs, m):
        out = dict(msgs)
        out[m] = out.get(m, 0) + 1
        return frozenset(out.items())

    @staticmethod
    def _send_once(msgs, m):
        if m in msgs:
            return None
        out = dict(msgs)
        out[m] = 1
        return frozenset(out.items())

    def _ser_msgs(self, msgs) -> tuple:
        return tuple(sorted((self._norm_rec(m), c) for m, c in msgs))

    @staticmethod
    def _set(tup, i, val) -> tuple:
        return tup[:i] + (val,) + tup[i + 1 :]

    @staticmethod
    def _with(st, **updates) -> dict:
        out = dict(st)
        out.update(updates)
        return out

    def bfs(
        self,
        invariants: tuple[str, ...] = (
            "LeaderHasAllAckedValues",
            "NoLogDivergence",
            "MaxOneReconfigurationAtATime",
        ),
        symmetry: bool = True,
        max_depth: int | None = None,
        max_states: int | None = None,
        time_budget_s: float | None = None,
    ) -> dict:
        import time

        t0 = time.perf_counter()
        init = self.init_state()
        seen = {self.canon(init, symmetry)}
        frontier = [init]
        total = 1
        distinct = 1
        depth_counts = [1]
        violation = None
        depth = 0
        while frontier and violation is None:
            if max_states and distinct >= max_states:
                break  # hard cap (the inner breaks alone admitted one
                # extra state per depth level past the cap)
            if max_depth is not None and depth >= max_depth:
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                break
            next_frontier = []
            for st in frontier:
                for _label, s2 in self.successors(st):
                    total += 1
                    key = self.canon(s2, symmetry)
                    if key in seen:
                        continue
                    seen.add(key)
                    distinct += 1
                    for inv in invariants:
                        if not self.INVARIANTS[inv](self, s2):
                            violation = {
                                "invariant": inv,
                                "state": s2,
                                "depth": depth + 1,
                            }
                            break
                    next_frontier.append(s2)
                    if violation or (max_states and distinct >= max_states):
                        break
                if violation or (max_states and distinct >= max_states):
                    break
                if (
                    time_budget_s is not None
                    and (total & 0x3FF) < 8
                    and time.perf_counter() - t0 > time_budget_s
                ):
                    break
            frontier = next_frontier
            if frontier:
                depth_counts.append(len(frontier))
            depth += 1
        return {
            "distinct": distinct,
            "total": total,
            "depth_counts": depth_counts,
            "violation": violation,
        }
