"""Independent pure-Python interpreter of pull-raft/KRaftWithReconfig.tla.

The largest reference spec (1,918 lines): KRaft plus one-at-a-time
reconfiguration over a DYNAMIC server universe of composite
``[host, diskId]`` identities. Written directly against the TLA+ text
(reference ``/root/reference/specifications/pull-raft/
KRaftWithReconfig.tla`` + the shared ``MessagePassing.tla`` it EXTENDS).

Key structure (SURVEY.md §2.1):
  - the ``servers`` universe GROWS: ``StartNewServer:1492`` and
    ``RestartWithoutState:906`` mint fresh ``[host, diskId]`` identities
    (``_diskIdGen``), bounded by ``MaxSpawnedServers``;
  - servers carry a ``role`` (Voter/Observer, ``:349-351``); roles flip
    via config commands in the log (``MaybeSwitchConfigurations:753``);
  - states add ``Resigned`` and the terminal ``DeadNoState``
    (``:354-360``);
  - joining is message-driven: ``SendJoinRequest:1524`` ->
    ``AcceptJoinRequest:1558`` (``JoinCheck:1551``) appends an
    AddServerCommand; removal is an admin action
    (``HandleRemoveRequest:1699``, ``RemoveCheck:1692``);
  - a leader that commits its own removal resigns inside
    ``AcceptFetchRequestFromVoter:1317-1324``;
  - ``MessagePassing.tla`` send-once classes: RequestVoteRequest,
    BeginQuorumRequest, JoinRequest (``:40-45``); Reply refuses duplicate
    FetchResponses (``:72-79``);
  - ``endOffset[i]``'s DOMAIN is itself dynamic state (extended by
    ``MaybeSwitchConfigurations:767-771`` and
    ``AcceptJoinRequest:1581``) and must round-trip exactly.

Faithfully-reproduced reference quirks (kept for parity, verified against
the TLA+ text):
  - ``RestartWithoutState:913`` tests ``state[j] = Voter`` — comparing a
    STATE to the ROLE model value Voter, which no state assignment ever
    produces, so the action is never enabled;
  - ``_addReconfigCtr`` is never incremented (only gated on,
    ``SendJoinRequest:1526``) — joins are instead bounded by the
    JoinRequest send-once latch and MaxClusterSize;
  - ``HandleRejectJoinResponse:1653-1672`` tests ``m.mresult`` (Ok/NotOk)
    against the ERROR values NotLeader/FencedLeaderEpoch, so only the
    OTHER arm (plain Discard) is reachable.

State dict format: identities are (host, diskId) tuples; per-server maps
are dicts keyed by identity; entries are (command, epoch, value) with
value = int v | (id, members) | (id, new/old identity, members).
"""

from __future__ import annotations

import itertools

from .config_oracle_base import ConfigOracleBase

# states (KRaftWithReconfig.tla:354-360) — string enums keep the oracle
# readable; the lowering maps them to small ints
UNATTACHED, FOLLOWER, CANDIDATE, LEADER, VOTED, RESIGNED, DEAD, ILLEGAL = (
    "Unattached",
    "Follower",
    "Candidate",
    "Leader",
    "Voted",
    "Resigned",
    "DeadNoState",
    "IllegalState",
)
VOTER, OBSERVER = "Voter", "Observer"  # roles (:349-351)

# errors (:375-376)
FENCED, NOT_LEADER, UNKNOWN_LEADER = (
    "FencedLeaderEpoch",
    "NotLeader",
    "UnknownLeader",
)
UNKNOWN_MEMBER, ALREADY_MEMBER, RECONFIG_IN_PROGRESS, LEADER_NOT_READY = (
    "UnknownMember",
    "AlreadyMember",
    "ReconfigInProgress",
    "LeaderNotReady",
)
OK, NOT_OK, DIVERGING = "Ok", "NotOk", "Diverging"

INIT_CMD = "InitClusterCommand"
APPEND_CMD = "AppendCommand"
ADD_CMD = "AddServerCommand"
REMOVE_CMD = "RemoveServerCommand"
CONFIG_CMDS = (INIT_CMD, ADD_CMD, REMOVE_CMD)

NO_CONFIG = (0, frozenset(), False)  # NoConfig (:737-740)


def rec(**kw) -> tuple:
    return tuple(sorted(kw.items()))


def last_epoch(log) -> int:
    """LastEpoch — :498."""
    return log[-1][1] if log else 0


def compare_entries(o1, e1, o2, e2) -> int:
    """CompareEntries — :513-517."""
    if e1 > e2:
        return 1
    if e1 == e2 and o1 > o2:
        return 1
    if e1 == e2 and o1 == o2:
        return 0
    return -1


def end_offset_for_epoch(log, lfe) -> tuple[int, int]:
    """EndOffsetForEpoch — :551-567."""
    best = 0
    for off in range(1, len(log) + 1):
        if log[off - 1][1] <= lfe:
            best = off
    return (best, log[best - 1][1]) if best else (0, 0)


def highest_common_offset(log, end_off, epoch) -> int:
    """HighestCommonOffset — :521-539."""
    best = 0
    for off in range(1, len(log) + 1):
        if compare_entries(off, log[off - 1][1], end_off, epoch) <= 0:
            best = off
    return best


def is_config_command(entry) -> bool:
    """IsConfigCommand — :718-721."""
    return entry[0] in CONFIG_CMDS


def most_recent_reconfig_entry(log) -> tuple[int, tuple]:
    """MostRecentReconfigEntry — :729-735."""
    best = 0
    for off in range(1, len(log) + 1):
        if is_config_command(log[off - 1]):
            best = off
    assert best > 0, "log has no config command"
    return best, log[best - 1]


def config_for(offset: int, entry: tuple, ci: int) -> tuple:
    """ConfigFor — :743-746."""
    val = entry[2]
    return (val[0], val[-1], ci >= offset)


class KRaftReconfigOracle(ConfigOracleBase):
    def __init__(
        self,
        n_hosts: int,
        n_values: int,
        init_cluster_size: int,
        min_cluster_size: int,
        max_cluster_size: int,
        max_elections: int,
        max_restarts: int,
        max_values_per_epoch: int,
        max_add_reconfigs: int,
        max_remove_reconfigs: int,
        max_spawned_servers: int,
    ):
        self.H = n_hosts
        self.V = n_values
        self.init_cluster_size = init_cluster_size
        self.min_cluster = min_cluster_size
        self.max_cluster = max_cluster_size
        self.max_elections = max_elections
        self.max_restarts = max_restarts
        self.max_values_per_epoch = max_values_per_epoch
        self.max_add = max_add_reconfigs
        self.max_remove = max_remove_reconfigs
        self.max_spawned = max_spawned_servers
        self.max_epoch = 1 + max_elections

    # ---------- state helpers ----------

    def init_state(self) -> dict:
        """Init — :845-859: pre-installed cluster; every initial member has
        diskId 0; CHOOSE realized as lowest host indices / identities."""
        members = frozenset((h, 0) for h in range(self.init_cluster_size))
        init_leader = min(members)
        first = (INIT_CMD, 1, (1, members))
        return {
            "servers": members,
            "config": {i: (1, members, True) for i in members},
            "currentEpoch": {i: 1 for i in members},
            "role": {i: VOTER for i in members},
            "state": {
                i: LEADER if i == init_leader else FOLLOWER for i in members
            },
            "leader": {i: init_leader for i in members},
            "votedFor": {i: None for i in members},
            "pendingFetch": {i: None for i in members},
            "votesGranted": {i: frozenset() for i in members},
            "endOffset": {i: {j: 1 for j in members} for i in members},
            "log": {i: (first,) for i in members},
            "highWatermark": {i: 1 for i in members},
            "messages": frozenset(),
            "_acked": (None,) * self.V,
            "_electionCtr": 0,
            "_valueCtr": (0,) * self.max_epoch,
            "_restartCtr": 0,
            "_addReconfigCtr": 0,
            "_removeReconfigCtr": 0,
            "_diskIdGen": 0,
        }

    @staticmethod
    def _setm(mapping: dict, i, val) -> dict:
        out = dict(mapping)
        out[i] = val
        return out

    # ---------- message-bag helpers (MessagePassing.tla) ----------

    @classmethod
    def _send(cls, msgs, m):
        """Send — MessagePassing.tla:40-45: RequestVoteRequest,
        BeginQuorumRequest and JoinRequest are send-once."""
        mtype = dict(m)["mtype"]
        if mtype in ("RequestVoteRequest", "BeginQuorumRequest", "JoinRequest"):
            return cls._send_once(msgs, m)
        return cls._send_no_restriction(msgs, m)

    @staticmethod
    def _reply(msgs, response, request):
        """Reply — MessagePassing.tla:72-79: a FetchResponse may not be
        duplicated."""
        out = dict(msgs)
        if out.get(request, 0) < 1:
            return None
        if response in out and dict(response)["mtype"] == "FetchResponse":
            return None
        out[request] -= 1
        out[response] = out.get(response, 0) + 1
        return frozenset(out.items())

    def _receivable(self, st, m, mtype: str, equal_epoch: bool) -> bool:
        """ReceivableMessage — :471-477 (adds the DeadNoState guard)."""
        d = dict(m)
        msgs = self._msgs(st)
        if msgs.get(m, 0) < 1 or d["mtype"] != mtype:
            return False
        if st["state"][d["mdest"]] == DEAD:
            return False
        if equal_epoch and d["mepoch"] != st["currentEpoch"][d["mdest"]]:
            return False
        return True

    @staticmethod
    def _norm_rec(m) -> tuple:
        def norm_val(v):
            if v is None:
                return (0, 0)
            if isinstance(v, bool):
                return (1, int(v))
            if isinstance(v, int):
                return (2, v)
            if isinstance(v, str):
                return (3, v)
            if isinstance(v, frozenset):
                return (4, tuple(sorted(v)))
            if isinstance(v, tuple) and v and isinstance(v[0], tuple) and len(
                v[0]
            ) == 2 and isinstance(v[0][0], str):
                return (5, KRaftReconfigOracle._norm_rec(v))
            if isinstance(v, tuple):
                return (6, tuple(norm_val(x) for x in v))
            raise TypeError(v)

        return tuple((k, norm_val(v)) for k, v in m)

    def _domain(self, st):
        return sorted((m for m, _c in st["messages"]), key=self._norm_rec)

    # ---------- transition machine (:599-715) ----------

    def _has_consistent_leader(self, st, i, leader_id, epoch) -> bool:
        """HasConsistentLeader — :599-616 (with the resigned/observer
        carve-outs)."""
        if leader_id == i:
            if st["currentEpoch"][i] == epoch and (
                st["role"][i] == OBSERVER or st["state"][i] == RESIGNED
            ):
                return True
            return st["state"][i] == LEADER
        return (
            epoch != st["currentEpoch"][i]
            or leader_id is None
            or st["leader"][i] is None
            or st["leader"][i] == leader_id
        )

    @staticmethod
    def _illegal():
        return {"state": ILLEGAL, "epoch": 0, "leader": None, "transitioned": True}

    def _no_transition(self, st, i):
        return {
            "state": st["state"][i],
            "epoch": st["currentEpoch"][i],
            "leader": st["leader"][i],
            "transitioned": False,
        }

    def _to_voted(self, st, i, epoch, state0):
        """TransitionToVoted — :630-637."""
        if state0["epoch"] == epoch and state0["state"] != UNATTACHED:
            return self._illegal()
        return {"state": VOTED, "epoch": epoch, "leader": None, "transitioned": True}

    @staticmethod
    def _to_unattached(epoch):
        return {
            "state": UNATTACHED,
            "epoch": epoch,
            "leader": None,
            "transitioned": True,
        }

    def _to_follower(self, st, i, leader_id, epoch):
        """TransitionToFollower — :645-653."""
        if st["currentEpoch"][i] == epoch and st["state"][i] in (FOLLOWER, LEADER):
            return self._illegal()
        return {
            "state": FOLLOWER,
            "epoch": epoch,
            "leader": leader_id,
            "transitioned": True,
        }

    def _maybe_transition(self, st, i, leader_id, epoch):
        """MaybeTransition — :656-675 (case 3 adds leaderId # i)."""
        if not self._has_consistent_leader(st, i, leader_id, epoch):
            return self._illegal()
        if epoch > st["currentEpoch"][i]:
            if leader_id is None:
                return self._to_unattached(epoch)
            return self._to_follower(st, i, leader_id, epoch)
        if leader_id is not None and st["leader"][i] is None and leader_id != i:
            return self._to_follower(st, i, leader_id, epoch)
        return self._no_transition(st, i)

    def _mhcr(self, st, i, leader_id, epoch, errors):
        """MaybeHandleCommonResponse — :683-715."""
        if epoch < st["currentEpoch"][i]:
            return self._no_transition(st, i) | {"handled": True, "error": errors}
        if epoch > st["currentEpoch"][i] or errors in (FENCED, NOT_LEADER):
            return self._maybe_transition(st, i, leader_id, epoch) | {
                "handled": True,
                "error": errors,
            }
        if (
            epoch == st["currentEpoch"][i]
            and leader_id is not None
            and st["leader"][i] is None
        ):
            return {
                "state": FOLLOWER,
                "leader": leader_id,
                "epoch": st["currentEpoch"][i],
                "transitioned": True,
                "handled": errors is not None,
                "error": errors,
            }
        return self._no_transition(st, i) | {"handled": False, "error": errors}

    # ---------- config machinery (:718-777) ----------

    def _has_pending_config(self, st, i) -> bool:
        return st["config"][i][2] is False

    def _leader_has_committed_in_epoch(self, st, i) -> bool:
        """LeaderHasCommittedOffsetsInCurrentEpoch — :774-777."""
        return any(
            st["log"][i][off - 1][1] == st["currentEpoch"][i]
            and st["highWatermark"][i] >= off
            for off in range(1, len(st["log"][i]) + 1)
        )

    def _maybe_switch_configurations(self, st, i, curr_config, new_state) -> dict:
        """MaybeSwitchConfigurations — :753-771: updates leader/config,
        flips Voter<->Observer on membership change, and pads endOffset's
        domain to all servers. Returns the field updates."""
        role_i = st["role"][i]
        members = curr_config[1]
        upd = {
            "leader": self._setm(st["leader"], i, new_state["leader"]),
            "config": self._setm(st["config"], i, curr_config),
        }
        if role_i == VOTER and i not in members:
            upd["role"] = self._setm(st["role"], i, OBSERVER)
            upd["state"] = self._setm(st["state"], i, FOLLOWER)
        elif role_i == OBSERVER and i in members:
            upd["role"] = self._setm(st["role"], i, VOTER)
            upd["state"] = self._setm(st["state"], i, FOLLOWER)
        else:
            upd["state"] = self._setm(st["state"], i, new_state["state"])
        eo = dict(st["endOffset"][i])
        for j in st["servers"]:
            if j not in eo:
                eo[j] = 0
        upd["endOffset"] = self._setm(st["endOffset"], i, eo)
        return upd

    def _set_state_of_new_identity(self, st, identity, first_fetch, dead=None):
        """SetStateOfNewAndDeadIdentity — :781-797."""
        upd = dict(
            servers=st["servers"] | {identity},
            config=self._setm(st["config"], identity, NO_CONFIG),
            currentEpoch=self._setm(st["currentEpoch"], identity, 0),
            leader=self._setm(st["leader"], identity, None),
            votedFor=self._setm(st["votedFor"], identity, None),
            pendingFetch=self._setm(st["pendingFetch"], identity, first_fetch),
            votesGranted=self._setm(st["votesGranted"], identity, frozenset()),
            endOffset=self._setm(
                st["endOffset"], identity, {j: 0 for j in st["servers"]}
            ),
            log=self._setm(st["log"], identity, ()),
            highWatermark=self._setm(st["highWatermark"], identity, 0),
        )
        role = self._setm(st["role"], identity, OBSERVER)
        state = self._setm(st["state"], identity, UNATTACHED)
        if dead is not None:
            role[dead] = DEAD
            state[dead] = DEAD
        upd["role"] = role
        upd["state"] = state
        return upd

    def _valid_fetch_position(self, st, i, d) -> bool:
        """ValidFetchPosition — :571-576."""
        if d["mfetchOffset"] == 0 and d["mlastFetchedEpoch"] == 0:
            return True
        off, ep = end_offset_for_epoch(st["log"][i], d["mlastFetchedEpoch"])
        return d["mfetchOffset"] <= off and d["mlastFetchedEpoch"] == ep

    # ---------- actions (Next order, :1730-1756) ----------

    def successors(self, st) -> list[tuple[str, dict]]:
        out = []
        servers = sorted(st["servers"])
        domain = self._domain(st)  # hoisted: 13 receipt loops share one sort
        for i in servers:
            s2 = self.restart_with_state(st, i)
            if s2 is not None:
                out.append((f"RestartWithState({i})", s2))
        # RestartWithoutState (:906-924) is never enabled: its guard
        # compares state[j] to the ROLE value Voter (:913), which no state
        # assignment produces — reproduced faithfully as a no-op.
        for i in servers:
            s2 = self.request_vote(st, i)
            if s2 is not None:
                out.append((f"RequestVote({i})", s2))
        for m in domain:
            s2 = self.handle_request_vote_request(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteRequest", s2))
        for m in domain:
            s2 = self.handle_request_vote_response(st, m)
            if s2 is not None:
                out.append(("HandleRequestVoteResponse", s2))
        for i in servers:
            s2 = self.become_leader(st, i)
            if s2 is not None:
                out.append((f"BecomeLeader({i})", s2))
        for i in servers:
            for v in range(self.V):
                s2 = self.client_request(st, i, v)
                if s2 is not None:
                    out.append((f"ClientRequest({i},{v})", s2))
        for m in domain:
            s2 = self.reject_fetch_request(st, m)
            if s2 is not None:
                out.append(("RejectFetchRequest", s2))
        for m in domain:
            s2 = self.diverging_fetch_request(st, m)
            if s2 is not None:
                out.append(("DivergingFetchRequest", s2))
        for m in domain:
            s2 = self.accept_fetch_request_from_voter(st, m)
            if s2 is not None:
                out.append(("AcceptFetchRequestFromVoter", s2))
        for m in domain:
            s2 = self.accept_fetch_request_from_observer(st, m)
            if s2 is not None:
                out.append(("AcceptFetchRequestFromObserver", s2))
        for m in domain:
            s2 = self.accept_begin_quorum_request(st, m)
            if s2 is not None:
                out.append(("AcceptBeginQuorumRequest", s2))
        for i in servers:
            for j in servers:
                if i != j:
                    s2 = self.send_fetch_request(st, i, j)
                    if s2 is not None:
                        out.append((f"SendFetchRequest({i},{j})", s2))
        for m in domain:
            s2 = self.handle_success_fetch_response(st, m)
            if s2 is not None:
                out.append(("HandleSuccessFetchResponse", s2))
        for m in domain:
            s2 = self.handle_diverging_fetch_response(st, m)
            if s2 is not None:
                out.append(("HandleDivergingFetchResponse", s2))
        for m in domain:
            s2 = self.handle_non_success_fetch_response(st, m)
            if s2 is not None:
                out.append(("HandleNonSuccessFetchResponse", s2))
        for h in range(self.H):
            for j in servers:
                s2 = self.start_new_server(st, h, j)
                if s2 is not None:
                    out.append((f"StartNewServer({h},{j})", s2))
        for i in servers:
            for j in servers:
                if i != j:
                    s2 = self.send_join_request(st, i, j)
                    if s2 is not None:
                        out.append((f"SendJoinRequest({i},{j})", s2))
        for m in domain:
            s2 = self.accept_join_request(st, m)
            if s2 is not None:
                out.append(("AcceptJoinRequest", s2))
        for m in domain:
            s2 = self.reject_join_request(st, m)
            if s2 is not None:
                out.append(("RejectJoinRequest", s2))
        for m in domain:
            s2 = self.handle_reject_join_response(st, m)
            if s2 is not None:
                out.append(("HandleRejectJoinResponse", s2))
        for i in servers:
            for r in servers:
                s2 = self.handle_remove_request(st, i, r)
                if s2 is not None:
                    out.append((f"HandleRemoveRequest({i},{r})", s2))
        return out

    def restart_with_state(self, st, i):
        """RestartWithState — :873-896: a leader restarts as Resigned
        (voter) or Unattached (observer); keeps epoch/role/votedFor/log."""
        if st["_restartCtr"] >= self.max_restarts:
            return None
        if st["state"][i] == DEAD:
            return None
        was_leader = st["state"][i] == LEADER
        if was_leader and st["role"][i] == VOTER:
            new_state = RESIGNED
        elif was_leader and st["role"][i] == OBSERVER:
            new_state = UNATTACHED
        else:
            new_state = st["state"][i]
        return self._with(
            st,
            state=self._setm(st["state"], i, new_state),
            leader=self._setm(
                st["leader"], i, None if was_leader else st["leader"][i]
            ),
            votesGranted=self._setm(st["votesGranted"], i, frozenset()),
            endOffset=self._setm(
                st["endOffset"], i, {j: 0 for j in st["servers"]}
            ),
            highWatermark=self._setm(st["highWatermark"], i, 0),
            pendingFetch=self._setm(st["pendingFetch"], i, None),
            _restartCtr=st["_restartCtr"] + 1,
        )

    def request_vote(self, st, i):
        """RequestVote — :932-955: Voter only, member of own config."""
        if st["_electionCtr"] >= self.max_elections:
            return None
        if st["role"][i] != VOTER:
            return None
        if st["state"][i] not in (FOLLOWER, CANDIDATE, UNATTACHED):
            return None
        if i not in st["config"][i][1]:
            return None
        new_epoch = st["currentEpoch"][i] + 1
        reqs = {
            rec(
                mtype="RequestVoteRequest",
                mepoch=new_epoch,
                mlastLogEpoch=last_epoch(st["log"][i]),
                mlastLogOffset=len(st["log"][i]),
                msource=i,
                mdest=j,
            )
            for j in st["config"][i][1]
            if j != i
        }
        msgs = self._send_multiple_once(self._msgs(st), reqs)
        if msgs is None:
            return None
        return self._with(
            st,
            state=self._setm(st["state"], i, CANDIDATE),
            currentEpoch=self._setm(st["currentEpoch"], i, new_epoch),
            leader=self._setm(st["leader"], i, None),
            votedFor=self._setm(st["votedFor"], i, i),
            votesGranted=self._setm(st["votesGranted"], i, frozenset({i})),
            pendingFetch=self._setm(st["pendingFetch"], i, None),
            _electionCtr=st["_electionCtr"] + 1,
            messages=msgs,
        )

    def handle_request_vote_request(self, st, m):
        """HandleRequestVoteRequest — :967-1018."""
        if not self._receivable(st, m, "RequestVoteRequest", equal_epoch=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        error = FENCED if d["mepoch"] < st["currentEpoch"][i] else None
        if error is not None:
            resp = rec(
                mtype="RequestVoteResponse",
                mepoch=st["currentEpoch"][i],
                mleader=st["leader"][i],
                mvoteGranted=False,
                merror=error,
                msource=i,
                mdest=j,
            )
            msgs = self._reply(self._msgs(st), resp, m)
            if msgs is None:
                return None
            return self._with(st, messages=msgs)
        state0 = (
            self._to_unattached(d["mepoch"])
            if d["mepoch"] > st["currentEpoch"][i]
            else self._no_transition(st, i)
        )
        log_ok = (
            compare_entries(
                d["mlastLogOffset"],
                d["mlastLogEpoch"],
                len(st["log"][i]),
                last_epoch(st["log"][i]),
            )
            >= 0
        )
        grant = (
            state0["state"] == UNATTACHED
            or (state0["state"] == VOTED and st["votedFor"][i] == j)
        ) and log_ok
        final = (
            self._to_voted(st, i, d["mepoch"], state0)
            if grant and state0["state"] == UNATTACHED
            else state0
        )
        resp = rec(
            mtype="RequestVoteResponse",
            mepoch=d["mepoch"],
            mleader=final["leader"],
            mvoteGranted=grant,
            merror=None,
            msource=i,
            mdest=j,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        upd = dict(
            state=self._setm(st["state"], i, final["state"]),
            currentEpoch=self._setm(st["currentEpoch"], i, final["epoch"]),
            leader=self._setm(st["leader"], i, final["leader"]),
            messages=msgs,
        )
        if grant:
            upd["votedFor"] = self._setm(st["votedFor"], i, j)
        if final["state"] != st["state"][i]:
            upd["pendingFetch"] = self._setm(st["pendingFetch"], i, None)
        return self._with(st, **upd)

    def handle_request_vote_response(self, st, m):
        """HandleRequestVoteResponse — :1025-1050 (adds the Voter gate)."""
        if not self._receivable(st, m, "RequestVoteResponse", equal_epoch=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["role"][i] != VOTER:
            return None
        new = self._mhcr(st, i, d["mleader"], d["mepoch"], d["merror"])
        msgs = self._discard(self._msgs(st), m)
        if new["handled"]:
            return self._with(
                st,
                state=self._setm(st["state"], i, new["state"]),
                leader=self._setm(st["leader"], i, new["leader"]),
                currentEpoch=self._setm(st["currentEpoch"], i, new["epoch"]),
                messages=msgs,
            )
        if st["state"][i] != CANDIDATE:
            return None
        vg = (
            st["votesGranted"][i] | {j}
            if d["mvoteGranted"]
            else st["votesGranted"][i]
        )
        return self._with(
            st, votesGranted=self._setm(st["votesGranted"], i, vg), messages=msgs
        )

    def become_leader(self, st, i):
        """BecomeLeader — :1056-1071."""
        if st["state"][i] != CANDIDATE:
            return None
        members = st["config"][i][1]
        vg = st["votesGranted"][i]
        if not (vg <= members and 2 * len(vg) > len(members)):
            return None
        reqs = {
            rec(
                mtype="BeginQuorumRequest",
                mepoch=st["currentEpoch"][i],
                msource=i,
                mdest=j,
            )
            for j in members
            if j != i
        }
        msgs = self._send_multiple_once(self._msgs(st), reqs)
        if msgs is None:
            return None
        return self._with(
            st,
            state=self._setm(st["state"], i, LEADER),
            leader=self._setm(st["leader"], i, i),
            endOffset=self._setm(
                st["endOffset"], i, {j: 0 for j in st["servers"]}
            ),
            messages=msgs,
        )

    def accept_begin_quorum_request(self, st, m):
        """AcceptBeginQuorumRequest — :1082-1102: Voter only; stale
        requests are NOT answered (unlike KRaft.tla)."""
        if not self._receivable(st, m, "BeginQuorumRequest", equal_epoch=False):
            return None
        d = dict(m)
        i = d["mdest"]
        if d["mepoch"] < st["currentEpoch"][i]:  # error # Nil -> not enabled
            return None
        if st["role"][i] != VOTER:
            return None
        new = self._maybe_transition(st, i, d["msource"], d["mepoch"])
        return self._with(
            st,
            state=self._setm(st["state"], i, new["state"]),
            leader=self._setm(st["leader"], i, new["leader"]),
            currentEpoch=self._setm(st["currentEpoch"], i, new["epoch"]),
            pendingFetch=self._setm(st["pendingFetch"], i, None),
            messages=self._discard(self._msgs(st), m),
        )

    def client_request(self, st, i, v):
        """ClientRequest — :1110-1126."""
        if st["state"][i] != LEADER or st["_acked"][v] is not None:
            return None
        epoch = st["currentEpoch"][i]
        if st["_valueCtr"][epoch - 1] >= self.max_values_per_epoch:
            return None
        entry = (APPEND_CMD, epoch, v)
        vc = list(st["_valueCtr"])
        vc[epoch - 1] += 1
        return self._with(
            st,
            log=self._setm(st["log"], i, st["log"][i] + (entry,)),
            _acked=self._set_tuple(st["_acked"], v, False),
            _valueCtr=tuple(vc),
        )

    @staticmethod
    def _set_tuple(tup, i, val):
        return tup[:i] + (val,) + tup[i + 1 :]

    def send_fetch_request(self, st, i, j):
        """SendFetchRequest — :1137-1169: known-leader follower fetch, or
        an Unattached observer probing a random voter of its config."""
        if st["pendingFetch"][i] is not None:
            return None
        path_a = st["leader"][i] == j and st["state"][i] == FOLLOWER
        path_b = (
            st["role"][i] == OBSERVER
            and st["state"][i] == UNATTACHED
            and j in st["config"][i][1]
        )
        if not (path_a or path_b):
            return None
        fetch = rec(
            mtype="FetchRequest",
            mepoch=st["currentEpoch"][i],
            mfetchOffset=len(st["log"][i]),
            mlastFetchedEpoch=last_epoch(st["log"][i]),
            mobserver=st["role"][i] == OBSERVER,
            msource=i,
            mdest=j,
        )
        msgs = self._send(self._msgs(st), fetch)
        if msgs is None:
            return None
        return self._with(
            st,
            pendingFetch=self._setm(st["pendingFetch"], i, fetch),
            messages=msgs,
        )

    def reject_fetch_request(self, st, m):
        """RejectFetchRequest — :1195-1217."""
        if not self._receivable(st, m, "FetchRequest", equal_epoch=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER:
            error = NOT_LEADER
        elif d["mepoch"] < st["currentEpoch"][i]:
            error = FENCED
        elif d["mepoch"] > st["currentEpoch"][i]:
            error = UNKNOWN_LEADER
        else:
            return None
        resp = rec(
            mtype="FetchResponse",
            mresult=NOT_OK,
            merror=error,
            mleader=st["leader"][i],
            mepoch=st["currentEpoch"][i],
            mhwm=st["highWatermark"][i],
            msource=i,
            mdest=j,
            correlation=m,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def diverging_fetch_request(self, st, m):
        """DivergingFetchRequest — :1225-1248."""
        if not self._receivable(st, m, "FetchRequest", equal_epoch=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER or self._valid_fetch_position(st, i, d):
            return None
        off, ep = end_offset_for_epoch(st["log"][i], d["mlastFetchedEpoch"])
        resp = rec(
            mtype="FetchResponse",
            mepoch=st["currentEpoch"][i],
            mresult=DIVERGING,
            merror=None,
            mdivergingEpoch=ep,
            mdivergingEndOffset=off,
            mleader=st["leader"][i],
            mhwm=st["highWatermark"][i],
            msource=i,
            mdest=j,
            correlation=m,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def _new_hwm(self, st, i, new_end: dict) -> int:
        """NewHighwaterMark — :1266-1284 (leader self-exclusion when not a
        member)."""
        members = st["config"][i][1]
        best = 0
        for off in range(1, len(st["log"][i]) + 1):
            agree = {k for k in members if new_end.get(k, 0) >= off}
            if i in members:
                agree |= {i}
            if agree <= members and 2 * len(agree) > len(members):
                best = off
        if best > 0 and st["log"][i][best - 1][1] == st["currentEpoch"][i]:
            return best
        return st["highWatermark"][i]

    def accept_fetch_request_from_voter(self, st, m):
        """AcceptFetchRequestFromVoter — :1286-1342: advances the hwm, may
        commit a config, and resigns on committing its own removal."""
        if not self._receivable(st, m, "FetchRequest", equal_epoch=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER or d["mobserver"]:
            return None
        if not self._valid_fetch_position(st, i, d):
            return None
        offset = d["mfetchOffset"] + 1
        log_i = st["log"][i]
        entries = () if offset > len(log_i) else (log_i[offset - 1],)
        new_end = dict(st["endOffset"][i])
        new_end[j] = d["mfetchOffset"]
        new_hwm = self._new_hwm(st, i, new_end)
        hwm_old = st["highWatermark"][i]
        # IsRemovedFromCluster (:1259-1264)
        leaves = any(
            log_i[off - 1][0] == REMOVE_CMD and i not in log_i[off - 1][2][-1]
            for off in range(hwm_old + 1, new_hwm + 1)
        )
        upd = {}
        if new_hwm > hwm_old:
            cfg_off, cfg_entry = most_recent_reconfig_entry(log_i)
            upd["config"] = self._setm(
                st["config"], i, config_for(cfg_off, cfg_entry, new_hwm)
            )
            acked = list(st["_acked"])
            committed_vals = {
                log_i[off - 1][2]
                for off in range(hwm_old + 1, new_hwm + 1)
                if log_i[off - 1][0] == APPEND_CMD
            }
            for v in range(self.V):
                if st["_acked"][v] is False:
                    acked[v] = v in committed_vals
            upd["_acked"] = tuple(acked)
            if leaves:
                upd["role"] = self._setm(st["role"], i, OBSERVER)
                upd["state"] = self._setm(st["state"], i, UNATTACHED)
                upd["leader"] = self._setm(st["leader"], i, None)
                upd["votesGranted"] = self._setm(
                    st["votesGranted"], i, frozenset()
                )
                upd["endOffset"] = self._setm(
                    st["endOffset"], i, {s: 0 for s in st["servers"]}
                )
                upd["highWatermark"] = self._setm(st["highWatermark"], i, 0)
            else:
                upd["endOffset"] = self._setm(st["endOffset"], i, new_end)
                upd["highWatermark"] = self._setm(
                    st["highWatermark"], i, new_hwm
                )
        else:
            upd["endOffset"] = self._setm(st["endOffset"], i, new_end)
            leaves = False
        resp = rec(
            mtype="FetchResponse",
            mepoch=st["currentEpoch"][i],
            mleader=None if leaves else st["leader"][i],
            mresult=OK,
            merror=None,
            mentries=entries,
            mhwm=min(new_hwm, offset),
            msource=i,
            mdest=j,
            correlation=m,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs, **upd)

    def accept_fetch_request_from_observer(self, st, m):
        """AcceptFetchRequestFromObserver — :1349-1376: no local state
        change, just a response."""
        if not self._receivable(st, m, "FetchRequest", equal_epoch=True):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if st["state"][i] != LEADER or not d["mobserver"]:
            return None
        if not self._valid_fetch_position(st, i, d):
            return None
        offset = d["mfetchOffset"] + 1
        log_i = st["log"][i]
        entries = () if offset > len(log_i) else (log_i[offset - 1],)
        resp = rec(
            mtype="FetchResponse",
            mepoch=st["currentEpoch"][i],
            mleader=st["leader"][i],
            mresult=OK,
            merror=None,
            mentries=entries,
            mhwm=min(offset, st["highWatermark"][i]),
            msource=i,
            mdest=j,
            correlation=m,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def handle_success_fetch_response(self, st, m):
        """HandleSuccessFetchResponse — :1383-1409."""
        if not self._receivable(st, m, "FetchResponse", equal_epoch=False):
            return None
        d = dict(m)
        i = d["mdest"]
        if d["mresult"] != OK:
            return None
        new = self._mhcr(st, i, d["mleader"], d["mepoch"], d["merror"])
        if new["handled"] or st["pendingFetch"][i] != d["correlation"]:
            return None
        log_i = st["log"][i]
        if len(d["mentries"]) > 0:
            log_i = log_i + (d["mentries"][0],)
        cfg_off, cfg_entry = most_recent_reconfig_entry(log_i)
        curr_config = config_for(cfg_off, cfg_entry, d["mhwm"])
        upd = self._maybe_switch_configurations(st, i, curr_config, new)
        upd["highWatermark"] = self._setm(st["highWatermark"], i, d["mhwm"])
        upd["log"] = self._setm(st["log"], i, log_i)
        upd["pendingFetch"] = self._setm(st["pendingFetch"], i, None)
        upd["messages"] = self._discard(self._msgs(st), m)
        return self._with(st, **upd)

    def handle_diverging_fetch_response(self, st, m):
        """HandleDivergingFetchResponse — :1419-1445."""
        if not self._receivable(st, m, "FetchResponse", equal_epoch=False):
            return None
        d = dict(m)
        i = d["mdest"]
        if d["mresult"] != DIVERGING:
            return None
        new = self._mhcr(st, i, d["mleader"], d["mepoch"], d["merror"])
        if new["handled"] or st["pendingFetch"][i] != d["correlation"]:
            return None
        hco = highest_common_offset(
            st["log"][i], d["mdivergingEndOffset"], d["mdivergingEpoch"]
        )
        new_log = st["log"][i][:hco]
        cfg_off, cfg_entry = most_recent_reconfig_entry(new_log)
        curr_config = config_for(cfg_off, cfg_entry, d["mhwm"])
        upd = self._maybe_switch_configurations(st, i, curr_config, new)
        upd["log"] = self._setm(st["log"], i, new_log)
        upd["pendingFetch"] = self._setm(st["pendingFetch"], i, None)
        upd["messages"] = self._discard(self._msgs(st), m)
        return self._with(st, **upd)

    def handle_non_success_fetch_response(self, st, m):
        """HandleNonSuccessFetchResponse — :1459-1483 (UnknownMember
        demotes to Observer)."""
        if not self._receivable(st, m, "FetchResponse", equal_epoch=False):
            return None
        d = dict(m)
        i = d["mdest"]
        new = self._mhcr(st, i, d["mleader"], d["mepoch"], d["merror"])
        if not new["handled"] or st["pendingFetch"][i] != d["correlation"]:
            return None
        upd = dict(
            state=self._setm(st["state"], i, new["state"]),
            leader=self._setm(st["leader"], i, new["leader"]),
            currentEpoch=self._setm(st["currentEpoch"], i, new["epoch"]),
            pendingFetch=self._setm(st["pendingFetch"], i, None),
            messages=self._discard(self._msgs(st), m),
        )
        if d["merror"] == UNKNOWN_MEMBER:
            upd["role"] = self._setm(st["role"], i, OBSERVER)
        return self._with(st, **upd)

    # ---------- reconfiguration (:1492-1724) ----------

    def start_new_server(self, st, host, any_leader):
        """StartNewServer — :1492-1511: mints a fresh [host, diskId]
        observer identity whose first fetch targets a current leader."""
        if len(st["servers"]) >= self.max_spawned:
            return None
        if st["state"][any_leader] != LEADER:
            return None
        disk_id = st["_diskIdGen"] + 1
        identity = (host, disk_id)
        fetch = rec(
            mtype="FetchRequest",
            mepoch=0,
            mfetchOffset=0,
            mlastFetchedEpoch=0,
            mobserver=True,
            msource=identity,
            mdest=any_leader,
        )
        msgs = self._send(self._msgs(st), fetch)
        if msgs is None:
            return None
        upd = self._set_state_of_new_identity(st, identity, fetch)
        upd["_diskIdGen"] = disk_id
        upd["messages"] = msgs
        return self._with(st, **upd)

    def send_join_request(self, st, i, j):
        """SendJoinRequest — :1524-1538 (gated on _addReconfigCtr, which
        the spec never increments — reproduced faithfully)."""
        if st["_addReconfigCtr"] >= self.max_add:
            return None
        if st["role"][i] != OBSERVER:
            return None
        if i in st["config"][i][1]:
            return None
        if st["leader"][i] != j:
            return None
        msg = rec(
            mtype="JoinRequest",
            mepoch=st["currentEpoch"][i],
            mdest=j,
            msource=i,
        )
        msgs = self._send(self._msgs(st), msg)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def _join_check(self, st, i, m):
        """JoinCheck — :1551-1556."""
        d = dict(m)
        if st["state"][i] != LEADER:
            return NOT_LEADER
        if d["msource"] in st["config"][i][1]:
            return ALREADY_MEMBER
        if self._has_pending_config(st, i):
            return RECONFIG_IN_PROGRESS
        if not self._leader_has_committed_in_epoch(st, i):
            return LEADER_NOT_READY
        return OK

    def accept_join_request(self, st, m):
        """AcceptJoinRequest — :1558-1590."""
        if not self._receivable(st, m, "JoinRequest", equal_epoch=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        if len(st["config"][i][1]) >= self.max_cluster:
            return None
        if self._join_check(st, i, m) != OK:
            return None
        cfg_id, members, _c = st["config"][i]
        entry = (
            ADD_CMD,
            st["currentEpoch"][i],
            (cfg_id + 1, j, members | {j}),
        )
        new_log = st["log"][i] + (entry,)
        resp = rec(
            mtype="JoinResponse",
            mepoch=st["currentEpoch"][i],
            mleader=st["leader"][i],
            mresult=OK,
            merror=None,
            mdest=j,
            msource=i,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        eo = dict(st["endOffset"][i])
        if j not in eo:
            eo[j] = 0
        return self._with(
            st,
            log=self._setm(st["log"], i, new_log),
            config=self._setm(
                st["config"],
                i,
                config_for(len(new_log), entry, st["highWatermark"][i]),
            ),
            endOffset=self._setm(st["endOffset"], i, eo),
            messages=msgs,
        )

    def reject_join_request(self, st, m):
        """RejectJoinRequest — :1605-1623: only NotLeader/AlreadyMember are
        answered; ReconfigInProgress/LeaderNotReady stay unanswered."""
        if not self._receivable(st, m, "JoinRequest", equal_epoch=False):
            return None
        d = dict(m)
        i, j = d["mdest"], d["msource"]
        check = self._join_check(st, i, m)
        if check not in (NOT_LEADER, ALREADY_MEMBER):
            return None
        resp = rec(
            mtype="JoinResponse",
            mepoch=st["currentEpoch"][i],
            mleader=st["leader"][i],
            mresult=NOT_OK,
            merror=check,
            mdest=j,
            msource=i,
        )
        msgs = self._reply(self._msgs(st), resp, m)
        if msgs is None:
            return None
        return self._with(st, messages=msgs)

    def handle_reject_join_response(self, st, m):
        """HandleRejectJoinResponse — :1643-1674. The first two CASE arms
        test m.mresult against the ERROR values NotLeader/FencedLeaderEpoch
        (:1654,:1664) — mresult is only ever Ok/NotOk, so only the OTHER
        arm (a plain Discard) is reachable; reproduced faithfully."""
        if not self._receivable(st, m, "JoinResponse", equal_epoch=False):
            return None
        d = dict(m)
        i = d["mdest"]
        if st["role"][i] != OBSERVER:
            return None
        if d["mresult"] != NOT_OK:
            return None
        return self._with(st, messages=self._discard(self._msgs(st), m))

    def handle_remove_request(self, st, i, remove_server):
        """HandleRemoveRequest — :1699-1724: admin-initiated removal; a
        self-removing leader becomes an observer but stays leader."""
        if st["_removeReconfigCtr"] >= self.max_remove:
            return None
        if self._remove_check(st, i, remove_server) != OK:
            return None
        if len(st["config"][i][1]) <= self.min_cluster:
            return None
        cfg_id, members, _c = st["config"][i]
        entry = (
            REMOVE_CMD,
            st["currentEpoch"][i],
            (cfg_id + 1, remove_server, members - {remove_server}),
        )
        new_log = st["log"][i] + (entry,)
        upd = dict(
            log=self._setm(st["log"], i, new_log),
            config=self._setm(
                st["config"],
                i,
                config_for(len(new_log), entry, st["highWatermark"][i]),
            ),
            _removeReconfigCtr=st["_removeReconfigCtr"] + 1,
        )
        if i == remove_server:
            upd["role"] = self._setm(st["role"], i, OBSERVER)
        return self._with(st, **upd)

    def _remove_check(self, st, i, j):
        """RemoveCheck — :1692-1697."""
        if st["state"][i] != LEADER:
            return NOT_LEADER
        if j not in st["config"][i][1]:
            return UNKNOWN_MEMBER
        if self._has_pending_config(st, i):
            return RECONFIG_IN_PROGRESS
        if not self._leader_has_committed_in_epoch(st, i):
            return LEADER_NOT_READY
        return OK

    # ---------- VIEW + SYMMETRY ----------

    def _ser_entry(self, e):
        cmd, ep, val = e
        if cmd == APPEND_CMD:
            return (cmd, ep, (val,))
        if cmd == INIT_CMD:
            return (cmd, ep, (val[0], tuple(sorted(val[1]))))
        return (cmd, ep, (val[0], val[1], tuple(sorted(val[2]))))

    def serialize_view(self, st) -> tuple:
        """view — :460: everything except the _-prefixed aux vars, but
        including _acked."""
        servers = tuple(sorted(st["servers"]))
        ack = {None: -1, False: 0, True: 1}

        def by_server(field, default=None, f=lambda x: x):
            return tuple(f(st[field][i]) for i in servers)

        return (
            servers,
            by_server("config", f=lambda c: (c[0], tuple(sorted(c[1])), c[2])),
            by_server("currentEpoch"),
            by_server("role"),
            by_server("state"),
            by_server("votedFor", f=lambda v: v if v is not None else ()),
            by_server("leader", f=lambda v: v if v is not None else ()),
            by_server(
                "pendingFetch", f=lambda p: self._norm_rec(p) if p else ()
            ),
            by_server("votesGranted", f=lambda vs: tuple(sorted(vs))),
            by_server("endOffset", f=lambda eo: tuple(sorted(eo.items()))),
            by_server("log", f=lambda lg: tuple(self._ser_entry(e) for e in lg)),
            by_server("highWatermark"),
            tuple(sorted((self._norm_rec(m), c) for m, c in st["messages"])),
            tuple(ack[a] for a in st["_acked"]),
        )

    def serialize_full(self, st) -> tuple:
        return self.serialize_view(st) + (
            st["_electionCtr"],
            st["_valueCtr"],
            st["_restartCtr"],
            st["_addReconfigCtr"],
            st["_removeReconfigCtr"],
            st["_diskIdGen"],
        )

    def permute(self, st, sigma, tau=None) -> dict:
        """Apply a host permutation sigma (and optional value permutation
        tau) — symmHostsAndValues (:462-463). Identities map
        (host, diskId) -> (sigma[host], diskId)."""
        tau = tau or list(range(self.V))

        def pid(i):
            return None if i is None else (sigma[i[0]], i[1])

        def pentry(e):
            cmd, ep, val = e
            if cmd == APPEND_CMD:
                return (cmd, ep, tau[val])
            if cmd == INIT_CMD:
                return (cmd, ep, (val[0], frozenset(pid(x) for x in val[1])))
            return (
                cmd,
                ep,
                (val[0], pid(val[1]), frozenset(pid(x) for x in val[2])),
            )

        def pmsg(m):
            d = dict(m)
            d["msource"] = pid(d["msource"])
            d["mdest"] = pid(d["mdest"])
            if d.get("mleader") is not None:
                d["mleader"] = pid(d["mleader"])
            if "mentries" in d:
                d["mentries"] = tuple(pentry(e) for e in d["mentries"])
            if "correlation" in d:
                d["correlation"] = pmsg(d["correlation"])
            return rec(**d)

        def pmap(field, f=lambda x: x):
            return {pid(i): f(v) for i, v in st[field].items()}

        return self._with(
            st,
            servers=frozenset(pid(i) for i in st["servers"]),
            config=pmap(
                "config",
                f=lambda c: (c[0], frozenset(pid(x) for x in c[1]), c[2]),
            ),
            currentEpoch=pmap("currentEpoch"),
            role=pmap("role"),
            state=pmap("state"),
            votedFor=pmap("votedFor", f=pid),
            leader=pmap("leader", f=pid),
            pendingFetch=pmap(
                "pendingFetch", f=lambda p: pmsg(p) if p is not None else None
            ),
            votesGranted=pmap(
                "votesGranted", f=lambda vs: frozenset(pid(x) for x in vs)
            ),
            endOffset=pmap(
                "endOffset", f=lambda eo: {pid(j): v for j, v in eo.items()}
            ),
            log=pmap("log", f=lambda lg: tuple(pentry(e) for e in lg)),
            highWatermark=pmap("highWatermark"),
            messages=frozenset((pmsg(m), c) for m, c in st["messages"]),
            _acked=tuple(st["_acked"][tau.index(v)] for v in range(self.V)),
        )

    def canon(self, st, symmetry: bool = True) -> tuple:
        if not symmetry:
            return self.serialize_view(st)
        best = None
        for sigma in itertools.permutations(range(self.H)):
            for tau in itertools.permutations(range(self.V)):
                key = self.serialize_view(self.permute(st, list(sigma), list(tau)))
                if best is None or key < best:
                    best = key
        return best

    # ---------- invariants (:1848-1912) ----------

    def no_illegal_state(self, st) -> bool:
        """NoIllegalState — :1848-1850."""
        return all(s != ILLEGAL for s in st["state"].values())

    def no_log_divergence(self, st) -> bool:
        """NoLogDivergence — :1860-1868."""
        servers = sorted(st["servers"])
        for a in servers:
            for b in servers:
                if a == b:
                    continue
                hwm = min(st["highWatermark"][a], st["highWatermark"][b])
                for off in range(1, hwm + 1):
                    if st["log"][a][off - 1] != st["log"][b][off - 1]:
                        return False
        return True

    def states_match_roles(self, st) -> bool:
        """StatesMatchRoles — :1876-1881."""
        observer_states = {LEADER, FOLLOWER, UNATTACHED, VOTED}
        for i in st["servers"]:
            if st["role"][i] == OBSERVER and st["state"][i] not in observer_states:
                return False
            if st["state"][i] == UNATTACHED and st["leader"][i] is not None:
                return False
        return True

    def never_two_leaders_in_same_epoch(self, st) -> bool:
        """NeverTwoLeadersInSameEpoch — :1886-1892."""
        servers = sorted(st["servers"])
        for a in servers:
            for b in servers:
                if (
                    a != b
                    and st["leader"][a] is not None
                    and st["leader"][b] is not None
                    and st["leader"][a] != st["leader"][b]
                    and st["currentEpoch"][a] == st["currentEpoch"][b]
                ):
                    return False
        return True

    def leader_has_all_acked_values(self, st) -> bool:
        """LeaderHasAllAckedValues — :1896-1912."""
        for v in range(self.V):
            if st["_acked"][v] is not True:
                continue
            for i in st["servers"]:
                if st["state"][i] != LEADER:
                    continue
                if any(
                    st["currentEpoch"][l] > st["currentEpoch"][i]
                    for l in st["servers"]
                    if l != i
                ):
                    continue
                if not any(
                    e[0] == APPEND_CMD and e[2] == v for e in st["log"][i]
                ):
                    return False
        return True

    def messages_are_valid(self, st) -> bool:
        """MessagesAreValid — MessagePassing.tla:81-83 (checker
        self-check)."""
        return not any(
            dict(m)["msource"] == dict(m)["mdest"] for m, _c in st["messages"]
        )

    INVARIANTS = {
        "NoIllegalState": no_illegal_state,
        "NoLogDivergence": no_log_divergence,
        "StatesMatchRoles": states_match_roles,
        "NeverTwoLeadersInSameEpoch": never_two_leaders_in_same_epoch,
        "LeaderHasAllAckedValues": leader_has_all_acked_values,
        "MessagesAreValid": messages_are_valid,
        "TestInv": lambda self, st: True,
    }

    # ---------- BFS / simulation ----------

    def bfs(
        self,
        invariants: tuple[str, ...] = (
            "LeaderHasAllAckedValues",
            "NoLogDivergence",
            "NeverTwoLeadersInSameEpoch",
            "NoIllegalState",
            "StatesMatchRoles",
        ),
        symmetry: bool = True,
        max_depth: int | None = None,
        max_states: int | None = None,
        time_budget_s: float | None = None,
    ) -> dict:
        import time

        t0 = time.perf_counter()
        init = self.init_state()
        seen = {self.canon(init, symmetry)}
        frontier = [init]
        total = 1
        distinct = 1
        depth_counts = [1]
        violation = None
        depth = 0
        while frontier and violation is None:
            if max_depth is not None and depth >= max_depth:
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                break
            next_frontier = []
            for st in frontier:
                for _label, s2 in self.successors(st):
                    total += 1
                    key = self.canon(s2, symmetry)
                    if key in seen:
                        continue
                    seen.add(key)
                    distinct += 1
                    for inv in invariants:
                        if not self.INVARIANTS[inv](self, s2):
                            violation = {
                                "invariant": inv,
                                "state": s2,
                                "depth": depth + 1,
                            }
                            break
                    next_frontier.append(s2)
                    if violation or (max_states and distinct >= max_states):
                        break
                if violation or (max_states and distinct >= max_states):
                    break
                if (
                    time_budget_s is not None
                    and (total & 0x3FF) < 8
                    and time.perf_counter() - t0 > time_budget_s
                ):
                    break
            frontier = next_frontier
            if frontier:
                depth_counts.append(len(frontier))
            depth += 1
        return {
            "distinct": distinct,
            "total": total,
            "depth_counts": depth_counts,
            "violation": violation,
        }

    def simulate(
        self,
        invariants: tuple[str, ...] = (
            "LeaderHasAllAckedValues",
            "NoLogDivergence",
            "NeverTwoLeadersInSameEpoch",
            "NoIllegalState",
            "StatesMatchRoles",
        ),
        behaviors: int = 100,
        max_depth: int = 50,
        seed: int = 0,
    ) -> dict:
        """TLC -simulate equivalent: random behaviors (the cfg's own header
        prescribes simulation for this spec)."""
        import random

        rng = random.Random(seed)
        steps = 0
        violation = None
        completed = 0
        for _b in range(behaviors):
            st = self.init_state()
            for depth in range(max_depth):
                succ = self.successors(st)
                if not succ:
                    break
                _label, st = rng.choice(succ)
                steps += 1
                for inv in invariants:
                    if not self.INVARIANTS[inv](self, st):
                        violation = {
                            "invariant": inv,
                            "state": st,
                            "depth": depth + 1,
                        }
                        break
                if violation:
                    break
            completed += 1
            if violation:
                break
        return {"behaviors": completed, "steps": steps, "violation": violation}
