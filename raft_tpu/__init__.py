"""raft_tpu — a TPU-native explicit-state model checker for the Raft TLA+ suite.

This package re-provides, TPU-first, the full model-checking capability that
the reference repo (Vanlightly/raft-tlaplus, mounted at /root/reference)
obtains from TLC: per-variant `Next` relations hand-lowered to vectorized JAX
transition kernels over a packed fixed-width state encoding, BFS frontier
expansion via `vmap`, VIEW/SYMMETRY-aware 64-bit fingerprint dedup, batched
invariant predicates, counterexample trace reconstruction, and frontier
sharding across a `jax.sharding.Mesh`.

Layout of the package:
  models/    per-variant spec lowerings (state layout + action kernels +
             invariants), e.g. models/raft.py for
             reference specifications/standard-raft/Raft.tla
  ops/       spec-agnostic device ops: bit packing, message-bag ops,
             symmetry canonicalization, 64-bit fingerprint hashing
  checker/   BFS driver, dedup, trace reconstruction, simulation mode
  parallel/  sharded-frontier expansion over a device mesh (ICI all-to-all)
  oracle/    independent pure-Python interpreters of the TLA+ semantics,
             used for differential testing (TLC itself is not vendored)
  utils/     TLC `.cfg` parser, pretty printers
"""

import os

import jax

# 64-bit fingerprints (TLC uses 64-bit state fingerprints; parity requires
# the same collision budget). Must run before any jax arrays are created.
jax.config.update("jax_enable_x64", True)

_compcache_checked = False


def enable_compcache() -> None:
    """Persistent compilation cache, TPU backend ONLY.

    The TPU tunnel's remote-compile service costs ~20 s per program
    shape (measured round 4 — even a 64k-lane sort-concat), and the
    checker's LSM merge ladder + chunk programs span a dozen shapes, so
    cold processes paid minutes of pure compile; the on-disk cache drops
    repeat compiles to ~0.1 s across processes. It is NOT enabled for
    the CPU backend: XLA:CPU cache entries written by tunnel-connected
    processes carry mismatched target-machine features
    (+prefer-no-scatter etc.) and ABORT on load (observed SIGABRT in
    AllToAllThunk). Called lazily once the backend is known, from
    Canonicalizer.for_model/__init__, Simulator and LivenessChecker —
    the chokepoints every checker/simulation path goes through. Override
    the location with RAFT_TPU_COMPCACHE (empty string disables)."""
    global _compcache_checked
    if _compcache_checked:
        return
    _compcache_checked = True
    cache_dir = os.environ.get(
        "RAFT_TPU_COMPCACHE",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache",
        ),
    )
    if not cache_dir:
        return
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return
    if platform == "cpu":
        return
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


__version__ = "0.1.0"
