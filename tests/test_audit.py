"""Checker self-checks: device MessagesAreValid (all models) and the
two-hash-family fingerprint-collision audit (round-2 verdict item 7)."""

import jax
import numpy as np
import pytest

from raft_tpu.checker.audit import collision_audit
from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.models.raft import RaftParams, cached_model

SMALL = RaftParams(n_servers=2, n_values=1, max_elections=2, max_restarts=0, msg_slots=16)


def _models():
    from raft_tpu.models import joint_raft, kraft, kraft_reconfig, pull_raft, reconfig_raft

    yield "raft", cached_model(SMALL)
    yield "pull", pull_raft.cached_model(
        pull_raft.PullRaftParams(2, 1, 1, 0, msg_slots=16)
    )
    yield "kraft", kraft.cached_model(
        kraft.KRaftParams(2, 1, 1, 0, msg_slots=16)
    )
    yield "joint", joint_raft.cached_model(
        joint_raft.JointRaftParams(
            n_servers=2, n_values=1, init_cluster_size=2, max_elections=1,
            max_restarts=0, max_reconfigs=0, max_values_per_term=1,
            reconfig_type=1, msg_slots=24,
        )
    )
    yield "reconfig", reconfig_raft.cached_model(
        reconfig_raft.ReconfigRaftParams(
            n_servers=2, n_values=1, init_cluster_size=2, max_elections=1,
            max_restarts=0, max_values_per_term=1, max_add_reconfigs=0,
            max_remove_reconfigs=0, min_cluster_size=2, max_cluster_size=2,
            msg_slots=24,
        )
    )
    yield "kraft_reconfig", kraft_reconfig.cached_model(
        kraft_reconfig.KRaftReconfigParams(
            n_hosts=2, n_values=1, init_cluster_size=2, min_cluster_size=2,
            max_cluster_size=2, max_elections=1, max_restarts=0,
            max_values_per_epoch=1, max_add_reconfigs=0,
            max_remove_reconfigs=0, max_spawned_servers=3, msg_slots=16,
        )
    )


@pytest.mark.slow
def test_messages_are_valid_on_reachable_states():
    """Every device model exposes MessagesAreValid; it must hold on all
    reachable states of a small bounded run (the spec never self-sends,
    MessagePassing.tla:81-83)."""
    for name, model in _models():
        assert "MessagesAreValid" in model.invariants, name
        res = BFSChecker(
            model, invariants=("MessagesAreValid",), symmetry=False, chunk=256
        ).run(max_depth=4)
        assert res.violation is None, name


def test_messages_are_valid_catches_corrupt_key():
    """A hand-corrupted self-addressed bag record must trip the check."""
    model = cached_model(SMALL)
    lay, pk = model.layout, model.packer
    vec = np.asarray(model.init_states())[0].copy()
    hi, lo = pk.pack(mtype=1, mterm=1, msource=1, mdest=1)  # self-addressed
    vec[lay.fields["msg_hi"].offset] = hi
    vec[lay.fields["msg_lo"].offset] = lo
    vec[lay.fields["msg_cnt"].offset] = 1
    ok = np.asarray(jax.device_get(model.invariants["MessagesAreValid"](vec[None])))
    assert not ok[0]
    clean = np.asarray(model.init_states())
    ok2 = np.asarray(jax.device_get(model.invariants["MessagesAreValid"](clean)))
    assert ok2.all()


@pytest.mark.slow
def test_collision_audit_passes_and_seeds_differ():
    model = cached_model(SMALL)
    res = collision_audit(
        model, invariants=(), symmetry=True, depth=6, chunk=256,
        frontier_cap=1 << 10, seen_cap=1 << 13, journal_cap=1 << 13,
    )
    assert res.ok, res
    # the two hash families really are different functions
    from raft_tpu.ops.symmetry import Canonicalizer

    init = model.init_states()
    fp_a = np.asarray(jax.device_get(
        Canonicalizer.for_model(model, symmetry=True, seed=0).fingerprints(init)))
    fp_b = np.asarray(jax.device_get(
        Canonicalizer.for_model(model, symmetry=True, seed=0x5EED5EED).fingerprints(init)))
    assert (fp_a != fp_b).all()


def test_collision_audit_slot_canonicalizer_seed():
    """The KRaftWithReconfig slot canonicalizer honors the audit seed."""
    from raft_tpu.models import kraft_reconfig

    model = kraft_reconfig.cached_model(
        kraft_reconfig.KRaftReconfigParams(
            n_hosts=2, n_values=1, init_cluster_size=2, min_cluster_size=2,
            max_cluster_size=2, max_elections=1, max_restarts=0,
            max_values_per_epoch=1, max_add_reconfigs=0,
            max_remove_reconfigs=0, max_spawned_servers=3, msg_slots=16,
        )
    )
    init = model.init_states()
    a = np.asarray(jax.device_get(
        model.make_canonicalizer(True, seed=0).fingerprints(init)))
    b = np.asarray(jax.device_get(
        model.make_canonicalizer(True, seed=1).fingerprints(init)))
    assert (a != b).all()
