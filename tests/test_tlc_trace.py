"""Golden-file test for the TLC error-trace emitter (--trace-format tlc).

The trace is the committed-value prefix of the documented split-brain
history (standard-raft/README.md:86-150; tests/test_split_brain_regression.py
replays the full behavior), replayed through the reconfig oracle and
formatted in TLC's textual error-trace shape: `Error:` headers, then
`State N: <action>` blocks of `/\\ var = value` lines in TLA+ value
syntax. This is the artifact a JVM-equipped user diffs against a real
`tlc` run (normalizing TLC's file line/col spans in action labels);
the golden file locks the format.
"""

import os
from types import SimpleNamespace

from raft_tpu.oracle.reconfig_oracle import ReconfigRaftOracle
from raft_tpu.utils.pprint import format_trace_tlc

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "split_brain_tlc.txt")


def build_trace():
    o = ReconfigRaftOracle(5, 1, 3, 1, 0, 1, 2, 2, 2, 5)
    st = o.init_state()
    trace = [("Initial predicate", st)]

    def step(prefix, pick=None):
        nonlocal st
        for label, s2 in o.successors(st):
            if label.startswith(prefix) and (pick is None or pick(s2)):
                st = s2
                trace.append((label, s2))
                return
        raise AssertionError(f"no successor matching {prefix!r}")

    # the README's step-0 prefix: commit a client value on the initial
    # cluster (majority {0, 2}; server 1 never receives it)
    step("ClientRequest(0,0)")
    step("AppendEntries(0,2)")
    step("AcceptAppendEntriesRequest")
    step("HandleAppendEntriesResponse")
    step("AdvanceCommitIndex(0)")
    assert st["acked"][0] is True
    return trace


def test_tlc_trace_matches_golden():
    setup = SimpleNamespace(
        server_names=["s1", "s2", "s3", "s4", "s5"], value_names=["v1"]
    )
    out = format_trace_tlc(build_trace(), setup, "LeaderHasAllAckedValues")
    assert out.startswith("Error: Invariant LeaderHasAllAckedValues is violated.\n"
                          "Error: The behavior up to this point is:\n")
    with open(GOLDEN) as f:
        want = f.read()
    assert out == want, "TLC trace format drifted from the golden file"
