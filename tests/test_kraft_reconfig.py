"""KRaftWithReconfig oracle tests: join/remove reconfiguration flows over
the dynamic server universe (pull-raft/KRaftWithReconfig.tla, 1,918
lines), invariants, bounded BFS sanity, simulation mode, and
reference-cfg loading with the documented v2 repair."""

import pytest

from pathlib import Path

from raft_tpu.oracle.kraft_reconfig_oracle import (
    FOLLOWER,
    LEADER,
    OBSERVER,
    RESIGNED,
    UNATTACHED,
    VOTER,
    KRaftReconfigOracle,
)


def small_oracle(**kw) -> KRaftReconfigOracle:
    defaults = dict(
        n_hosts=3, n_values=1, init_cluster_size=2, min_cluster_size=2,
        max_cluster_size=3, max_elections=1, max_restarts=1,
        max_values_per_epoch=1, max_add_reconfigs=1, max_remove_reconfigs=1,
        max_spawned_servers=4,
    )
    defaults.update(kw)
    return KRaftReconfigOracle(**defaults)


def step(o, st, prefix, pick=None):
    for label, s2 in o.successors(st):
        if label.startswith(prefix) and (pick is None or pick(s2)):
            return s2
    raise AssertionError(f"no successor matching {prefix!r}")


def test_init_state_shape():
    o = small_oracle()
    st = o.init_state()
    assert st["servers"] == frozenset({(0, 0), (1, 0)})
    leader = (0, 0)
    assert st["state"][leader] == LEADER
    assert st["role"][leader] == VOTER
    assert st["highWatermark"][leader] == 1
    assert all(o.INVARIANTS[n](o, st) for n in o.INVARIANTS)


def test_join_flow_new_server_becomes_voter():
    """StartNewServer -> observer fetch catch-up -> SendJoinRequest ->
    AcceptJoinRequest -> AddServerCommand replication -> role flip
    (:1492-1590, MaybeSwitchConfigurations :753-771)."""
    o = small_oracle()
    st = o.init_state()
    leader = (0, 0)
    # a new server starts on host 2 with diskId 1, fetching from the leader
    st = step(o, st, "StartNewServer(2,")
    new_id = (2, 1)
    assert new_id in st["servers"]
    assert st["role"][new_id] == OBSERVER
    assert st["state"][new_id] == UNATTACHED
    # leader accepts the observer's first fetch (epoch 0 < leader's 1 ->
    # rejected with FencedLeaderEpoch... actually mepoch=0 < 1 -> Reject)
    st = step(o, st, "RejectFetchRequest")
    st = step(o, st, "HandleNonSuccessFetchResponse")
    # after learning the leader+epoch, fetch catch-up
    assert st["leader"][new_id] == leader
    assert st["state"][new_id] == FOLLOWER
    st = step(o, st, f"SendFetchRequest({new_id},{leader})")
    st = step(o, st, "AcceptFetchRequestFromObserver")
    st = step(o, st, "HandleSuccessFetchResponse")
    assert len(st["log"][new_id]) == 1  # got the InitClusterCommand
    # join
    st = step(o, st, f"SendJoinRequest({new_id},{leader})")
    st = step(o, st, "AcceptJoinRequest")
    assert st["config"][leader][1] == frozenset({(0, 0), (1, 0), new_id})
    assert st["config"][leader][2] is False  # uncommitted
    # replicate the AddServerCommand to the new member
    st = step(o, st, f"SendFetchRequest({new_id},{leader})")
    st = step(o, st, "AcceptFetchRequestFromObserver")
    st = step(o, st, "HandleSuccessFetchResponse")
    # the new server sees itself in the config -> becomes Voter
    assert st["role"][new_id] == VOTER
    assert st["state"][new_id] == FOLLOWER
    # commit via voter fetches from the original follower: the first
    # ships the AddServerCommand, the second advances endOffset to 2
    st = step(o, st, f"SendFetchRequest({(1, 0)},{leader})")
    st = step(o, st, "AcceptFetchRequestFromVoter")
    st = step(o, st, "HandleSuccessFetchResponse")
    st = step(o, st, f"SendFetchRequest({(1, 0)},{leader})")
    st = step(o, st, "AcceptFetchRequestFromVoter")
    assert st["highWatermark"][leader] == 2
    assert st["config"][leader][2] is True
    assert all(o.INVARIANTS[n](o, st) for n in o.INVARIANTS)


def test_remove_leader_resigns_on_commit():
    """A leader that removes itself becomes an observer immediately
    (:1717-1719) and resigns once the command commits
    (:1317-1324): Unattached observer with hwm 0."""
    o = small_oracle(init_cluster_size=3, max_cluster_size=3)
    st = o.init_state()
    leader = (0, 0)
    st = step(o, st, f"HandleRemoveRequest({leader},{leader})")
    assert st["role"][leader] == OBSERVER
    assert st["state"][leader] == LEADER  # still acting leader
    members = st["config"][leader][1]
    assert leader not in members
    # replicate to both remaining voters; their endOffsets alone must
    # commit (leader excluded from the quorum, :1271-1274)
    for peer in ((1, 0), (2, 0)):
        st = step(o, st, f"SendFetchRequest({peer},{leader})")
        st = step(o, st, "AcceptFetchRequestFromVoter")
        st = step(o, st, "HandleSuccessFetchResponse")
    for peer in ((1, 0), (2, 0)):
        st = step(o, st, f"SendFetchRequest({peer},{leader})")
        st = step(o, st, "AcceptFetchRequestFromVoter")
    # the commit of its own removal made the leader resign
    assert st["state"][leader] == UNATTACHED
    assert st["role"][leader] == OBSERVER
    assert st["highWatermark"][leader] == 0
    assert all(o.INVARIANTS[n](o, st) for n in o.INVARIANTS)


def test_restart_with_state_leader_resigns():
    o = small_oracle()
    st = o.init_state()
    st = step(o, st, "RestartWithState((0, 0))")
    assert st["state"][(0, 0)] == RESIGNED
    assert st["leader"][(0, 0)] is None
    assert st["highWatermark"][(0, 0)] == 0
    assert len(st["log"][(0, 0)]) == 1  # log survives


def test_bounded_bfs_holds_invariants():
    o = small_oracle()
    res = o.bfs(symmetry=True, max_depth=3)
    assert res["violation"] is None
    assert res["distinct"] > 20
    # symmetry reduces the distinct count
    res_nosym = o.bfs(symmetry=False, max_depth=3)
    assert res_nosym["violation"] is None
    assert res_nosym["distinct"] >= res["distinct"]


def test_simulation_mode_runs_clean():
    o = small_oracle()
    res = o.simulate(behaviors=12, max_depth=12, seed=5)
    assert res["violation"] is None
    assert res["steps"] > 60


@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_reference_cfg_loads_with_v2_repair():
    from raft_tpu.utils.cfg import CfgError, parse_cfg
    from raft_tpu.models.registry import build_from_cfg, oracle_for_setup

    path = "/root/reference/specifications/pull-raft/KRaftWithReconfig.cfg"
    with pytest.raises(CfgError, match="undeclared model value 'v2'"):
        parse_cfg(path)
    cfg = parse_cfg(path, lenient=True)
    setup = build_from_cfg(cfg)
    assert setup.model.name == "KRaftWithReconfig"
    assert setup.model.p.n_hosts == 3
    assert setup.model.p.n_values == 2  # after repair
    assert setup.model.p.max_spawned_servers == 5
    assert setup.invariants == (
        "LeaderHasAllAckedValues",
        "NoLogDivergence",
        "NeverTwoLeadersInSameEpoch",
        "NoIllegalState",
        "StatesMatchRoles",
    )
    assert setup.symmetry
    oracle = oracle_for_setup(setup)
    # drive a few simulated behaviors on the real cfg constants
    res = oracle.simulate(
        invariants=setup.invariants, behaviors=4, max_depth=10, seed=1
    )
    assert res["violation"] is None


# ---------------------------------------------------------------------------
# Device lowering (models/kraft_reconfig.py): differential vs the oracle
# ---------------------------------------------------------------------------

import jax
import jax.numpy as jnp
import numpy as np

from conftest import collect_states
from raft_tpu.checker.device_bfs import DeviceBFS
from raft_tpu.models.kraft_reconfig import KRaftReconfigParams, cached_model

SMALLP = KRaftReconfigParams(
    n_hosts=3, n_values=1, init_cluster_size=2, min_cluster_size=2,
    max_cluster_size=3, max_elections=1, max_restarts=1,
    max_values_per_epoch=1, max_add_reconfigs=1, max_remove_reconfigs=1,
    max_spawned_servers=4, msg_slots=24,
)
DEV_INVS = (
    "NoIllegalState", "NoLogDivergence", "StatesMatchRoles",
    "NeverTwoLeadersInSameEpoch", "LeaderHasAllAckedValues",
)


def test_device_encode_decode_roundtrip():
    o = small_oracle()
    m = cached_model(SMALLP)
    st = o.init_state()
    # a state with a spawned server, pending fetch and join traffic
    st = step(o, st, "StartNewServer(2,")
    st = step(o, st, "RejectFetchRequest")
    st = step(o, st, "HandleNonSuccessFetchResponse")
    for s in (o.init_state(), st):
        rt = m.decode(m.encode(s))
        assert o.serialize_full(rt) == o.serialize_full(s)


@pytest.mark.slow
def test_device_successor_sets_match_oracle():
    """Successor-set differential on oracle-sampled reachable states
    (round-2 verdict item 4's 'done' bar)."""
    o = small_oracle()
    m = cached_model(SMALLP)
    states = collect_states(o, max_depth=4, cap=100)
    vecs = np.stack([m.encode(st) for st in states])
    succs, valid, rank, ovf = jax.device_get(m.expand(jnp.asarray(vecs)))
    assert not (valid & ovf).any()
    for b, st in enumerate(states):
        dev = {
            o.serialize_full(m.decode(succs[b, k]))
            for k in np.nonzero(valid[b])[0]
        }
        ora = {o.serialize_full(s2) for _l, s2 in o.successors(st)}
        assert dev == ora, f"state {b}: +{len(dev - ora)} -{len(ora - dev)}"


@pytest.mark.slow
@pytest.mark.parametrize("sym", [True, False])
def test_device_bfs_counts_match_oracle(sym):
    """Bounded-depth BFS count parity through the slot canonicalizer
    (host+value symmetry with data-dependent slot sort)."""
    o = small_oracle()
    m = cached_model(SMALLP)
    dev = DeviceBFS(
        m, invariants=DEV_INVS, symmetry=sym, chunk=256,
        frontier_cap=1 << 12, seen_cap=1 << 15, journal_cap=1 << 15,
    ).run(max_depth=4)
    ores = o.bfs(invariants=(), symmetry=sym, max_depth=4)
    assert dev.violation is None
    assert dev.distinct == ores["distinct"]
    assert dev.depth_counts == ores["depth_counts"]


@pytest.mark.slow
def test_device_symmetry_collapses_symmetric_init():
    """With a fully symmetric initial cluster (ics = H) the host
    permutations must collapse states exactly as the oracle's canon."""
    p = KRaftReconfigParams(
        n_hosts=3, n_values=1, init_cluster_size=3, min_cluster_size=2,
        max_cluster_size=4, max_elections=1, max_restarts=1,
        max_values_per_epoch=1, max_add_reconfigs=1, max_remove_reconfigs=1,
        max_spawned_servers=5, msg_slots=32,
    )
    o = small_oracle(init_cluster_size=3, max_cluster_size=4,
                     max_spawned_servers=5)
    m = cached_model(p)
    dev = DeviceBFS(
        m, invariants=(), symmetry=True, chunk=256,
        frontier_cap=1 << 12, seen_cap=1 << 15, journal_cap=1 << 15,
    ).run(max_depth=3)
    ores = o.bfs(invariants=(), symmetry=True, max_depth=3)
    nosym = o.bfs(invariants=(), symmetry=False, max_depth=3)
    assert dev.depth_counts == ores["depth_counts"]
    assert ores["distinct"] < nosym["distinct"]  # symmetry really reduces


@pytest.mark.slow
@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_device_cli_dispatch_tpu_checker():
    """--checker tpu now dispatches the reference cfg (device lowering
    replaces the round-1/2 'no TPU lowering yet' error path)."""
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    path = "/root/reference/specifications/pull-raft/KRaftWithReconfig.cfg"
    cfg = parse_cfg(path, lenient=True)
    setup = build_from_cfg(cfg, msg_slots=32)
    assert hasattr(setup.model, "expand")
    res = DeviceBFS(
        setup.model, invariants=setup.invariants, symmetry=True, chunk=256,
        frontier_cap=1 << 12, seen_cap=1 << 15, journal_cap=1 << 15,
    ).run(max_depth=2)
    assert res.violation is None
    assert res.distinct == 75  # pinned: depth-2 distinct on the real cfg
