"""Buffer donation must actually stick (round 6).

The wave program and the LSM merges declare donate_argnums so the big
HBM carries (next frontier, journal, seen runs, memo) update in place.
Donation that silently fails is worse than none: XLA copies the buffer
AND emits a UserWarning per dispatch. These tests pin:

  1. no donation warning anywhere in a full DeviceBFS / ShardedBFS run
     under ``-W error`` semantics (jit_with_donation probes each merge
     signature once and falls back to an undonated program where the
     backend cannot alias — e.g. truncate-merges on CPU);
  2. the wave program's donated inputs are really consumed
     (``.is_deleted()`` on the donated carries after a wave);
  3. two back-to-back ``run()`` calls on ONE engine instance produce
     identical results from cold carries — donation must not leak one
     run's buffers into the next.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.checker.device_bfs import DeviceBFS
from raft_tpu.checker.util import jit_with_donation
from raft_tpu.models.raft import RaftParams, cached_model

TINY = RaftParams(n_servers=2, n_values=1, max_elections=2, max_restarts=0, msg_slots=16)
INVS = ("LeaderHasAllAckedValues", "NoLogDivergence")


def _device(**kw):
    kw.setdefault("chunk", 256)
    kw.setdefault("frontier_cap", 1 << 12)
    kw.setdefault("seen_cap", 1 << 14)
    kw.setdefault("journal_cap", 1 << 14)
    return DeviceBFS(cached_model(TINY), invariants=INVS, symmetry=True, **kw)


def test_static_donation_audit_clean():
    """The static pin migrated to the donation lint pass: it lowers the
    wave program and reads the ``tf.aliasing_output`` attributes off
    the StableHLO ``@main`` signature, proving every declared carry
    really aliases an output (and the pinned frontier does not) —
    complementing the runtime ``is_deleted()`` probes below."""
    from raft_tpu.analysis import donation

    res = donation.run(families=("raft",), scopes=("device",))
    assert res.checked > 0
    assert not res.findings, [f.render() for f in res.findings]


def test_device_run_emits_no_donation_warning():
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        res = _device().run()
    assert res.exhausted and res.violation is None


@pytest.mark.slow
def test_sharded_run_emits_no_donation_warning():
    from raft_tpu.parallel.sharded import ShardedBFS

    engine = ShardedBFS(
        cached_model(TINY), invariants=INVS, symmetry=True,
        devices=jax.devices()[:1], chunk=256,
        frontier_cap=1 << 10, seen_cap=1 << 12,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        res = engine.run()
    assert res.exhausted and res.violation_invariant is None


def test_wave_program_consumes_donated_carries():
    """The wave program donates next_buf/journal/viol/stats/memo/cov
    (argnums 1..7): after a dispatch, those input buffers must be
    deleted — deleted means XLA aliased or freed them instead of keeping
    a live copy per wave."""
    dev = _device()
    W = dev.W
    frontier = jnp.zeros((dev.FCAP + dev.VC, W), jnp.int32)
    donated = dict(
        next_buf=jnp.zeros((dev.FCAP + dev.VC, W), jnp.int32),
        jparent=jnp.zeros((dev.JCAP + dev.VC,), jnp.int32),
        jcand=jnp.zeros((dev.JCAP + dev.VC,), jnp.int32),
        viol=jnp.full((len(INVS),), np.int32(2**31 - 1), jnp.int32),
        stats=jnp.zeros((6,), jnp.int64),
        memo=dev._memo.reset(),
        cov=jnp.zeros((dev.n_actions, 3), jnp.int64),
    )
    seen = jnp.full((dev._seen_sizes[0],), np.uint64(2**64 - 1), jnp.uint64)
    out = dev._wave_fn(
        frontier, *donated.values(), np.int32(0), np.int32(0),
        dev._occ_one, seen,
    )
    jax.block_until_ready(out)
    for name, buf in donated.items():
        assert buf.is_deleted(), f"donated carry {name} survived the wave"
    # the frontier (argnum 0) is NOT donated: the host swaps it with
    # next_buf between waves, so it must stay live
    assert not frontier.is_deleted()


def test_jit_with_donation_probe_and_fallback():
    """Plain same-shape programs donate (input deleted, no warning);
    programs XLA cannot alias on this backend fall back to an undonated
    jit instead of warning on every production call."""
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        # same-shape elementwise: always aliasable
        fn = jit_with_donation(
            lambda x: x + 1, (0,), lambda: (jnp.zeros((128,), jnp.int32),)
        )
        arg = jnp.zeros((128,), jnp.int32)
        out = fn(arg)
        jax.block_until_ready(out)
        if arg.is_deleted():
            donated = True
        else:
            donated = False  # backend declined: fallback path, no warning
        # either way, calling again must not warn
        out2 = fn(jnp.ones((128,), jnp.int32))
        jax.block_until_ready(out2)
        assert donated or not out2.is_deleted()


@pytest.mark.slow
def test_back_to_back_runs_identical():
    """One engine instance, two cold runs: donation must not leak the
    first run's carries (or its memo/seen contents) into the second."""
    dev = _device()
    r1 = dev.run(collect_metrics=True)
    r2 = dev.run(collect_metrics=True)
    assert r1.distinct == r2.distinct
    assert r1.depth_counts == r2.depth_counts
    assert r1.total == r2.total
    assert r1.terminal == r2.terminal
    assert r1.coverage == r2.coverage
    k1 = [{k: m[k] for k in ("new", "distinct", "generated")} for m in r1.metrics]
    k2 = [{k: m[k] for k in ("new", "distinct", "generated")} for m in r2.metrics]
    assert k1 == k2
