"""Test env: force CPU with 8 virtual devices so multi-chip sharding code
paths are exercised without TPU hardware.

Note: the image's axon TPU plugin overrides the JAX_PLATFORMS env var at
import time, so we must force the platform via jax.config AFTER importing
jax (but before any computation). XLA_FLAGS must still be set pre-import.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def collect_states(oracle, max_depth, cap=150):
    """Deterministic sample of reachable FULL states (dedup on full state),
    via the oracle's successor function. Shared by the differential tests."""
    seen = {}
    frontier = [oracle.init_state()]
    seen[oracle.serialize_full(frontier[0])] = frontier[0]
    for _ in range(max_depth):
        nxt = []
        for st in frontier:
            for _label, s2 in oracle.successors(st):
                k = oracle.serialize_full(s2)
                if k not in seen:
                    seen[k] = s2
                    nxt.append(s2)
            if len(seen) >= cap:
                break
        frontier = nxt
        if len(seen) >= cap:
            break
    return list(seen.values())
