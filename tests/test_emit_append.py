"""Emit parity: the round-6 contiguous cursor-append emit must be
bit-identical to the retired full-capacity scatter emit it replaced.

Three layers of evidence:
  1. unit parity of the emit helpers (checker/util.py dense_prefix_sel +
     emit_append) against a reference scatter, sweeping the cursor across
     the exactly-full and one-past-full capacity boundaries — the
     drop-lane overflow semantics the rewrite promised to preserve;
  2. engine parity on >= 2 models and both chunk geometries, host and
     device engines (identical counts, depth profile, terminal states,
     coverage table);
  3. engine-level overflow behavior: a journal/frontier capacity sized
     exactly to the run completes, one lane short raises OverflowError —
     the buffer-geometry change (pad rows past cap instead of one drop
     row at cap) must not shift the overflow threshold by a single row.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.checker.device_bfs import DeviceBFS
from raft_tpu.checker.util import dense_prefix_sel, emit_append
from raft_tpu.models.raft import RaftParams, cached_model

TINY = RaftParams(n_servers=2, n_values=1, max_elections=2, max_restarts=0, msg_slots=16)
SMALL = RaftParams(n_servers=3, n_values=1, max_elections=1, max_restarts=0, msg_slots=16)
INVS = ("LeaderHasAllAckedValues", "NoLogDivergence")


# ---------------- 1. unit parity of the emit helpers ----------------


def _reference_scatter(buf_rows, block_vals, new, count, cap):
    """The retired emit: arbitrary-index scatter with row `cap` as the
    drop lane (numpy mirror of the pre-round-6 _chunk_step step 5)."""
    npos = np.cumsum(new) - 1
    out = buf_rows.copy()
    for lane in range(len(new)):
        if new[lane]:
            dst = min(count + npos[lane], cap)
            out[dst] = block_vals[lane]
    ovf = count + int(new.sum()) > cap
    return out, ovf


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("cap,n_lanes", [(16, 8), (32, 8), (17, 8)])
def test_emit_append_matches_scatter_rows(seed, cap, n_lanes):
    """Sweep the cursor from empty through exactly-full to past-full:
    rows [0, cap) and the overflow flag must match the scatter path
    bit-for-bit at every cursor (the drop REGION [cap, cap+B) replaces
    the scatter's drop ROW cap; rows past cap are don't-care)."""
    rng = np.random.default_rng(seed)
    W = 3
    for count in range(0, cap + 2):
        new = rng.random(n_lanes) < 0.6
        n_new = int(new.sum())
        vals = rng.integers(1, 100, size=(n_lanes, W)).astype(np.int32)
        # reference: scatter into a (cap+1, W) buffer with drop row cap
        ref_buf = np.zeros((cap + 1, W), np.int32)
        ref, ref_ovf = _reference_scatter(ref_buf, vals, new, count, cap)
        # production: compact to a dense prefix block, append at cursor
        npos = jnp.asarray((np.cumsum(new) - 1).astype(np.int32))
        esel = dense_prefix_sel(jnp.asarray(new), npos, n_lanes)
        blk = jnp.concatenate(
            [jnp.asarray(vals), jnp.zeros((1, W), jnp.int32)], axis=0
        )[esel]
        buf = jnp.zeros((cap + n_lanes, W), jnp.int32)
        got, got_ovf = emit_append(
            buf, blk, jnp.int32(min(count, cap + 1)), jnp.int32(n_new), cap
        )
        assert bool(got_ovf) == ref_ovf, (count, n_new)
        np.testing.assert_array_equal(
            np.asarray(got)[:cap], ref[:cap],
            err_msg=f"cursor={count} n_new={n_new} rows [0, cap) diverged",
        )


def test_emit_append_1d_journal_parity():
    """Same boundary sweep for the 1-D journal-lane shape."""
    cap, n_lanes = 8, 4
    rng = np.random.default_rng(7)
    for count in range(0, cap + 2):
        new = rng.random(n_lanes) < 0.7
        n_new = int(new.sum())
        vals = rng.integers(1, 100, size=(n_lanes,)).astype(np.int32)
        ref_buf = np.zeros((cap + 1,), np.int32)
        ref, ref_ovf = _reference_scatter(
            ref_buf[:, None], vals[:, None], new, count, cap
        )
        npos = jnp.asarray((np.cumsum(new) - 1).astype(np.int32))
        esel = dense_prefix_sel(jnp.asarray(new), npos, n_lanes)
        blk = jnp.concatenate(
            [jnp.asarray(vals), jnp.zeros((1,), jnp.int32)]
        )[esel]
        buf = jnp.zeros((cap + n_lanes,), jnp.int32)
        got, got_ovf = emit_append(
            buf, blk, jnp.int32(min(count, cap + 1)), jnp.int32(n_new), cap
        )
        assert bool(got_ovf) == ref_ovf
        np.testing.assert_array_equal(np.asarray(got)[:cap], ref[:cap, 0])


def test_dense_prefix_sel_compacts_in_order():
    new = jnp.asarray([False, True, False, True, True, False])
    npos = jnp.cumsum(new).astype(jnp.int32) - 1
    sel = np.asarray(dense_prefix_sel(new, npos, 6))
    # first n_new entries are the new lanes in order; the rest point at
    # the caller's pad row (index n_lanes)
    assert sel[:3].tolist() == [1, 3, 4]
    assert (sel[3:] == 6).all()


# ---------------- 2. engine parity (>= 2 models x 2 chunk geometries) --


@pytest.mark.slow
@pytest.mark.parametrize("params", [TINY, SMALL], ids=["raft2", "raft3"])
@pytest.mark.parametrize("chunk", [256, 1024])
def test_append_emit_engine_parity(params, chunk):
    """Device (append emit) vs host (cursor-append buffers) end-to-end:
    counts, depth profile, terminal states and the coverage table must
    be identical across both models and both chunk geometries."""
    model = cached_model(params)
    host = BFSChecker(model, invariants=INVS, symmetry=True, chunk=chunk)
    hres = host.run()
    dev = DeviceBFS(
        model, invariants=INVS, symmetry=True, chunk=chunk,
        frontier_cap=1 << 14, seen_cap=1 << 17, journal_cap=1 << 17,
    )
    dres = dev.run()
    assert dres.violation is None and hres.violation is None
    assert dres.distinct == hres.distinct
    assert dres.depth_counts == hres.depth_counts
    assert dres.total == hres.total
    assert dres.terminal == hres.terminal
    assert dres.coverage == hres.coverage
    assert dres.exhausted


# ---------------- 3. engine-level overflow threshold ----------------


def _exact_journal_run(journal_cap):
    model = cached_model(TINY)
    dev = DeviceBFS(
        model, invariants=(), symmetry=True, chunk=256,
        frontier_cap=1 << 12, seen_cap=1 << 14,
        journal_cap=journal_cap, max_journal_cap=journal_cap,
    )
    return dev.run()


@pytest.mark.slow
def test_journal_overflow_threshold_exact():
    """journal_cap == distinct-beyond-init completes; one less raises.
    The append path's drop REGION must preserve the retired drop-row
    threshold to the single row."""
    base = _exact_journal_run(1 << 14)
    assert base.exhausted
    exact = base.distinct - base.depth_counts[0]
    res = _exact_journal_run(exact)
    assert res.exhausted and res.distinct == base.distinct
    with pytest.raises(OverflowError):
        _exact_journal_run(exact - 1)


@pytest.mark.slow
def test_frontier_overflow_threshold():
    """A frontier_cap below the widest wave aborts with the frontier
    overflow bit; at least the widest wave's lanes completes."""
    model = cached_model(TINY)
    base = DeviceBFS(
        model, invariants=(), symmetry=True, chunk=32,
        frontier_cap=1 << 12, seen_cap=1 << 14, journal_cap=1 << 14,
    ).run()
    assert base.exhausted
    widest = max(base.depth_counts)
    # cap below the widest wave (rounded to a chunk multiple, floored at
    # one chunk) must overflow rather than silently drop states
    small = max(32, (widest - 1) // 32 * 32)
    assert small < widest
    with pytest.raises(OverflowError):
        DeviceBFS(
            model, invariants=(), symmetry=True, chunk=32,
            frontier_cap=small, max_frontier_cap=small,
            seen_cap=1 << 14, journal_cap=1 << 14,
        ).run()
