"""Self-healing runtime tests (resilience/): crash-safe checkpoint
format, deterministic fault injection, the auto-resume supervisor, and
the CLI's exit-code contract.

The load-bearing gate is chaos PARITY: a supervised run that suffers an
injected crash, a torn checkpoint write, and a spurious frontier
overflow must end with counts bit-identical to a fault-free run —
exploration is deterministic, so recovery from a wave-start checkpoint
changes nothing but wall-clock. Host-engine parity runs in tier-1; the
device and sharded engines (and the real-SIGTERM subprocess drill) are
slow-marked, mirroring the existing checkpoint tests' tiering.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.models.raft import RaftParams, cached_model
from raft_tpu.resilience import (
    CapacityOverflow,
    ChaosInjector,
    ChaosSpec,
    CheckpointCorrupt,
    CheckpointMismatch,
    InjectedCrash,
    InjectedTransient,
    PreemptionGuard,
    UnrecoverableError,
    supervise,
)
from raft_tpu.resilience import ckpt as rckpt

RAFT2 = RaftParams(n_servers=2, n_values=2, max_elections=2,
                   max_restarts=0, msg_slots=16)


def _kraft():
    from raft_tpu.models.kraft import KRaftParams
    from raft_tpu.models.kraft import cached_model as kraft_cached

    return kraft_cached(KRaftParams(
        n_servers=3, n_values=1, max_elections=2, max_restarts=0,
        msg_slots=24,
    ))


def _first_inv(model):
    return tuple(list(model.invariants)[:1])


# ------------------------------------------------------- ckpt format


def _payload(depth=3):
    return dict(
        version=1,
        spec="test/spec/1",
        frontier=np.arange(12, dtype=np.int32).reshape(3, 4),
        seen=np.array([1, 2, 3], dtype=np.uint64),
        depth=depth,
    )


def test_ckpt_roundtrip_adds_version_and_hash(tmp_path):
    path = str(tmp_path / "a" / "b" / "ck.npz")  # parents auto-created
    rckpt.save_npz(path, _payload())
    loaded, gen, skipped = rckpt.load_npz(path)
    assert gen == 0 and skipped == []
    assert rckpt.format_version_of(loaded) == rckpt.FORMAT_VERSION
    assert int(loaded["depth"]) == 3
    np.testing.assert_array_equal(loaded["frontier"], _payload()["frontier"])


def test_ckpt_hash_catches_payload_corruption(tmp_path):
    path = str(tmp_path / "ck.npz")
    rckpt.save_npz(path, _payload(), keep=1)
    # flip one byte in the zip payload region; the zip container often
    # still parses, so only the content hash catches it
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size // 2)
        b = fh.read(1)
        fh.seek(size // 2)
        fh.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(CheckpointCorrupt):
        rckpt.load_npz(path, keep=1)


def test_ckpt_generation_rotation_and_fallback(tmp_path):
    path = str(tmp_path / "ck.npz")
    for d in (1, 2, 3):
        rckpt.save_npz(path, _payload(depth=d), keep=3)
    assert os.path.exists(path + ".gen1") and os.path.exists(path + ".gen2")
    # newest first: gen0=3, gen1=2, gen2=1
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 3)
    loaded, gen, skipped = rckpt.load_npz(path, keep=3)
    assert gen == 1 and int(loaded["depth"]) == 2
    assert len(skipped) == 1 and "ck.npz" in skipped[0]


def test_ckpt_all_generations_corrupt_lists_every_problem(tmp_path):
    path = str(tmp_path / "ck.npz")
    rckpt.save_npz(path, _payload(1), keep=2)
    rckpt.save_npz(path, _payload(2), keep=2)
    for p in (path, path + ".gen1"):
        with open(p, "r+b") as fh:
            fh.truncate(8)
    with pytest.raises(CheckpointCorrupt) as ei:
        rckpt.load_npz(path, keep=2)
    assert len(ei.value.problems) == 2


def test_ckpt_v1_file_loads_unverified(tmp_path):
    # pre-resilience files have no format_version/content_hash fields
    path = str(tmp_path / "old.npz")
    np.savez(path + ".tmp.npz", **_payload())
    os.replace(path + ".tmp.npz", path)
    loaded, gen, skipped = rckpt.load_npz(path)
    assert gen == 0 and skipped == []
    assert rckpt.format_version_of(loaded) == 1


def test_check_spec_mismatch_and_future_version(tmp_path):
    with pytest.raises(CheckpointMismatch, match="checkpoint is for spec"):
        rckpt.check_spec({"spec": "a"}, "b", "p.npz")
    # CheckpointMismatch IS a ValueError: pre-existing engine tests
    # match the same message with pytest.raises(ValueError)
    assert issubclass(CheckpointMismatch, ValueError)
    with pytest.raises(CheckpointMismatch, match="newer than this build"):
        rckpt.check_spec(
            {"spec": "a", "format_version": rckpt.FORMAT_VERSION + 1},
            "a", "p.npz")
    # a payload with no spec field fails with a sentence, not a KeyError
    with pytest.raises(CheckpointMismatch, match="missing spec"):
        rckpt.check_spec({}, "a", "p.npz")


def test_validate_resume_paths(tmp_path):
    with pytest.raises(FileNotFoundError):
        rckpt.validate_resume(str(tmp_path / "none.npz"), "x")
    path = str(tmp_path / "ck.npz")
    rckpt.save_npz(path, _payload(depth=5))
    assert rckpt.validate_resume(path, "test/spec/1") == (0, 5)
    with pytest.raises(CheckpointMismatch):
        rckpt.validate_resume(path, "other/spec")


def test_mesh_helpers_and_lineage_name():
    assert rckpt.mesh_d_of("sharded/D=8/raft/hashv=3") == 8
    assert rckpt.mesh_d_of("host/raft/hashv=3") is None
    assert rckpt.mesh_neutral("sharded/D=8/raft") == "sharded/raft"
    assert rckpt.mesh_neutral("sharded/D=4/raft") == rckpt.mesh_neutral(
        "sharded/D=1/raft")
    # lineage names disambiguate by fleet position: sanitizing alone
    # maps "a/b" and "a_b" to the same file (the collision this fixes)
    assert rckpt.lineage_name("a/b", 0) != rckpt.lineage_name("a_b", 1)
    assert rckpt.lineage_name("a/b", 0) == "a_b.j0.ckpt.npz"
    names = {rckpt.lineage_name(n, i)
             for i, n in enumerate(["a/b", "a_b", "a b"])}
    assert len(names) == 3


def test_check_spec_mesh_portability_gate():
    d4 = {"spec": "sharded/D=4/raft/hashv=3"}
    ident2 = "sharded/D=2/raft/hashv=3"
    # mesh-only mismatch: resharding allowed -> accepted
    rckpt.check_spec(d4, ident2, "p.npz", allow_reshard=True)
    # refused with a message naming BOTH mesh sizes and the reshard path
    with pytest.raises(CheckpointMismatch) as ei:
        rckpt.check_spec(d4, ident2, "p.npz")
    msg = str(ei.value)
    assert "D=4" in msg and "D=2" in msg and "mesh-portable" in msg
    # a real identity difference is never resharded over
    with pytest.raises(CheckpointMismatch, match="checkpoint is for spec"):
        rckpt.check_spec(
            {"spec": "sharded/D=4/raft/hashv=2"}, ident2, "p.npz",
            allow_reshard=True)


# ------------------------------------------------------- chaos harness


def test_chaos_spec_grammar():
    spec = ChaosSpec.parse("crash=3,truncate=2,seed=7")
    assert spec.crash == 3 and spec.truncate == 2 and spec.seed == 7
    assert "crash=3" in str(spec)
    for bad in ("crash", "crash=zero", "bogus=1", "crash=1,crash=2",
                "crash=0", "shard_loss=0"):
        with pytest.raises(ValueError):
            ChaosSpec.parse(bad)
    spec = ChaosSpec.parse("shard_loss=2,seed=5")
    assert spec.shard_loss == 2 and "shard_loss=2" in str(spec)


def test_chaos_shard_loss_hook_fires_once_and_is_seeded():
    inj = ChaosInjector(ChaosSpec.parse("shard_loss=2,seed=5"))
    assert inj.shard_loss(1, 4) is None
    assert inj.shard_loss(2, 4) == 5 % 4  # the doomed shard is seed % D
    assert inj.shard_loss(2, 4) is None  # consumed: resumes pass freely
    assert inj.fired == ["shard_loss"]


def test_chaos_faults_fire_exactly_once():
    inj = ChaosInjector(ChaosSpec.parse("crash=2,transient=3,ovf=4"))
    inj.wave_start(1)
    with pytest.raises(InjectedCrash):
        inj.wave_start(2)
    inj.wave_start(2)  # consumed: a resumed run passes wave 2 freely
    with pytest.raises(InjectedTransient):
        inj.wave_start(3)
    assert inj.ovf_bits(0, 4, frontier_bit=4) == 4
    assert inj.ovf_bits(0, 4, frontier_bit=4) == 0
    assert inj.ovf_bits(1, 5, frontier_bit=4) == 1  # real bits untouched


def test_chaos_truncates_nth_checkpoint_write(tmp_path):
    inj = ChaosInjector(ChaosSpec.parse("truncate=2"))
    path = str(tmp_path / "ck.npz")
    rckpt.save_npz(path, _payload(1), keep=3, chaos=inj)
    intact = os.path.getsize(path)
    rckpt.save_npz(path, _payload(2), keep=3, chaos=inj)  # 2nd write torn
    assert os.path.getsize(path) < intact
    loaded, gen, skipped = rckpt.load_npz(path, keep=3)
    assert gen == 1 and int(loaded["depth"]) == 1 and skipped


def test_preempt_guard_and_chaos_sigterm():
    with PreemptionGuard() as guard:
        assert not guard.requested
        inj = ChaosInjector(ChaosSpec.parse("preempt=2"))
        inj.wave_start(1)
        assert not guard.requested
        inj.wave_start(2)  # SIGTERM self-delivery
        assert guard.requested and guard.signame == "SIGTERM"
    # handler restored; a fresh guard starts clean
    assert not PreemptionGuard().requested


# ------------------------------------------------------- supervisor


class _Result:
    def __init__(self, exit_cause=None):
        self.exit_cause = exit_cause
        self.distinct = 42


class _ScriptedEngine:
    """Raises the scripted exceptions, one run() per entry, then wins."""

    def __init__(self, script, overrides, log):
        self.script = script
        self.overrides = overrides
        self.log = log

    def grow_for_overflow(self, bits):
        return None if bits & 1 else {"frontier_cap": 2048}

    def run(self, **kw):
        self.log.append(dict(overrides=self.overrides,
                             resume=kw.get("resume")))
        if self.script:
            raise self.script.pop(0)
        return _Result()


def _scripted_factory(script, log):
    return lambda overrides: _ScriptedEngine(script, overrides, log)


def test_supervise_overflow_grows_and_resumes(tmp_path):
    ck = str(tmp_path / "ck.npz")
    rckpt.save_npz(ck, _payload())
    log = []
    exc = CapacityOverflow("ovf", what=("frontier",), bits=4,
                           checkpoint_saved=True)
    res = supervise(_scripted_factory([exc], log),
                    {"checkpoint_path": ck}, backoff_base=0.0)
    assert res.distinct == 42
    assert log[0] == {"overrides": {}, "resume": None}
    assert log[1] == {"overrides": {"frontier_cap": 2048}, "resume": ck}


def test_supervise_overflow_without_checkpoint_restarts_fresh(tmp_path):
    # the sharded engine cannot save at its abort point; with no
    # checkpoint on disk the supervisor restarts fresh with grown caps
    log = []
    exc = CapacityOverflow("ovf", what=("frontier",), bits=4)
    res = supervise(
        _scripted_factory([exc], log),
        {"checkpoint_path": str(tmp_path / "never-written.npz")},
        backoff_base=0.0)
    assert res.distinct == 42
    assert log[1] == {"overrides": {"frontier_cap": 2048}, "resume": None}


def test_supervise_msg_slot_overflow_is_fatal():
    exc = CapacityOverflow("msg", what=("msg",), bits=1)
    with pytest.raises(UnrecoverableError, match="no growth policy"):
        supervise(_scripted_factory([exc], []), {}, backoff_base=0.0)


def test_supervise_retry_budget(tmp_path):
    ck = str(tmp_path / "ck.npz")
    rckpt.save_npz(ck, _payload())
    script = [InjectedCrash("boom") for _ in range(3)]
    with pytest.raises(UnrecoverableError, match="retry budget exhausted"):
        supervise(_scripted_factory(script, []),
                  {"checkpoint_path": ck},
                  max_retries=2, backoff_base=0.0)


def test_supervise_mismatch_is_fatal():
    with pytest.raises(CheckpointMismatch):
        supervise(_scripted_factory([CheckpointMismatch("wrong spec")], []),
                  {}, backoff_base=0.0)


def test_supervise_corrupt_resume_falls_back_to_fresh(tmp_path):
    ck = str(tmp_path / "ck.npz")
    rckpt.save_npz(ck, _payload())
    log = []
    script = [CheckpointCorrupt("torn", problems=("p",))]
    res = supervise(_scripted_factory(script, log),
                    {"checkpoint_path": ck, "resume": ck},
                    backoff_base=0.0)
    assert res.distinct == 42
    assert log[0]["resume"] == ck and log[1]["resume"] is None


def test_supervise_preempted_result_is_returned():
    log = []
    res = supervise(_scripted_factory([], log), {}, backoff_base=0.0)
    assert res.exit_cause is None
    engine = _ScriptedEngine([], {}, [])
    engine.run = lambda **kw: _Result(exit_cause="preempted")
    res = supervise(lambda o: engine, {}, backoff_base=0.0)
    assert res.exit_cause == "preempted"


class _MeshEngine(_ScriptedEngine):
    """Scripted engine with a 4-device mesh: shard loss hands the
    supervisor the survivor list, like ShardedBFS does."""

    devices = ["d0", "d1", "d2", "d3"]

    def survivors_for_shard_loss(self, shard):
        devs = [d for i, d in enumerate(self.devices) if i != shard % 4]
        return {"devices": devs} if len(self.devices) > 1 else None


def test_supervise_shard_lost_reshards_onto_survivors(tmp_path):
    from raft_tpu.resilience import ShardLost

    ck = str(tmp_path / "ck.npz")
    log, stats = [], {}
    exc = ShardLost("shard 2 lost", shard=2, checkpoint_saved=True)
    res = supervise(
        lambda ov: _MeshEngine([exc] if not ov else [], ov, log),
        {"checkpoint_path": ck}, backoff_base=0.0, stats_out=stats)
    assert res.distinct == 42
    # attempt 2 rebuilt on the D-1 survivor mesh and resumed the
    # wave-start checkpoint the engine spilled before raising
    assert log[1]["overrides"] == {"devices": ["d0", "d1", "d3"]}
    assert log[1]["resume"] == ck
    assert stats == {"recoveries": 1, "causes": ["shard-lost:2"]}


def test_supervise_shard_lost_single_device_is_fatal():
    from raft_tpu.resilience import ShardLost

    class _Solo(_ScriptedEngine):
        def survivors_for_shard_loss(self, shard):
            return None  # D=1: nobody left to reshard onto

    exc = ShardLost("shard 0 lost", shard=0, checkpoint_saved=True)
    with pytest.raises(UnrecoverableError, match="no surviving mesh"):
        supervise(lambda ov: _Solo([exc], ov, []), {}, backoff_base=0.0)


def test_supervise_shard_stall_resumes_same_mesh(tmp_path):
    from raft_tpu.resilience import ShardStall

    ck = str(tmp_path / "ck.npz")
    log, stats = [], {}
    exc = ShardStall("wave 5 stalled", shard=1, wave_s=9.0, median_s=1.0,
                     checkpoint_saved=True)
    res = supervise(_scripted_factory([exc], log),
                    {"checkpoint_path": ck}, backoff_base=0.0,
                    stats_out=stats)
    assert res.distinct == 42
    # a stall is a transient: same mesh (no overrides), resume
    assert log[1] == {"overrides": {}, "resume": ck}
    assert stats["causes"] == ["shard-stall:1"]


def test_supervise_emits_retry_events(tmp_path):
    ck = str(tmp_path / "ck.npz")
    rckpt.save_npz(ck, _payload())

    class _Tel:
        events = []

        def event(self, etype, **fields):
            self.events.append((etype, fields))

    script = [InjectedTransient("flake"), InjectedCrash("boom")]
    supervise(_scripted_factory(script, []), {"checkpoint_path": ck},
              backoff_base=0.0, telemetry=_Tel())
    kinds = [(e, f["attempt"], f["cause"]) for e, f in _Tel.events]
    assert kinds == [("retry", 1, "transient"), ("retry", 2, "crash")]


# ------------------------------------------------------- event schema


def test_resilience_events_validate():
    from raft_tpu.obs.events import validate_event, validate_lines

    good = [
        {"event": "retry", "attempt": 1, "cause": "crash",
         "backoff_s": 0.5, "growth": "-"},
        {"event": "resume", "path": "ck.npz", "generation": 1,
         "depth": 3, "distinct": 99},
        {"event": "ckpt_generation", "path": "ck.npz", "generation": 1,
         "skipped": ["gen0: torn"]},
        {"event": "preempt", "signame": "SIGTERM", "depth": 3,
         "checkpoint": "ck.npz"},
    ]
    for ev in good:
        assert validate_event(ev) == [], ev
    assert validate_event({"event": "retry", "attempt": 0, "cause": "c",
                           "backoff_s": 0, "growth": "-"})
    assert validate_event({"event": "ckpt_generation", "path": "p",
                           "generation": -1, "skipped": []})
    assert validate_event({"event": "preempt", "signame": "SIGTERM",
                           "depth": 0})  # missing checkpoint key
    # retry attempts must be strictly increasing within a session
    lines = [json.dumps({"event": "retry", "attempt": a, "cause": "c",
                         "backoff_s": 0.0, "growth": "-"})
             for a in (1, 1)]
    _, problems = validate_lines(lines)
    assert any("attempt" in p for p in problems)


def test_elastic_mesh_events_validate():
    from raft_tpu.obs.events import validate_event, validate_lines

    lost = {"event": "shard_lost", "wave": 3, "depth": 2, "shard": 1,
            "device_count": 4, "checkpoint_saved": True}
    resh = {"event": "reshard", "path": "ck.npz", "from_d": 4, "to_d": 2,
            "depth": 3, "distinct": 99}
    stall = {"event": "shard_stall", "wave": 5, "depth": 4, "shard": 0,
             "wave_s": 9.0, "median_wave_s": 1.0, "factor": 9.0}
    for ev in (lost, resh, stall):
        assert validate_event(ev) == [], ev
    # per-event field rules
    assert validate_event(dict(lost, shard=4))  # shard out of mesh range
    assert validate_event(dict(lost, device_count=0))
    assert validate_event(dict(resh, from_d=2))  # same-size "reshard"
    assert validate_event(dict(resh, to_d=0))
    assert validate_event(dict(stall, shard=-1))
    # structural: reshard belongs to the load phase, before any wave
    wave = {"event": "wave", "wave": 1}
    _, problems = validate_lines(
        [json.dumps(wave), json.dumps(resh)])
    assert any("before any wave" in p for p in problems)
    # shard_lost may not report a wave behind the last completed one
    _, problems = validate_lines(
        [json.dumps(dict(wave, wave=4)), json.dumps(lost)])
    assert any("behind" in p for p in problems)


# ------------------------------------------------------- host engine


def _host_run(model, inv, **kw):
    kw.setdefault("max_depth", 4)
    return BFSChecker(model, invariants=inv, symmetry=True,
                      chunk=256).run(**kw)


def _sig(res):
    return (res.distinct, res.total, res.depth,
            [int(x) for x in res.depth_counts], res.terminal, res.coverage)


def test_host_chaos_parity_crash_truncate_ovf(tmp_path):
    """The tier-1 chaos smoke: spurious overflow at wave 2, a torn
    checkpoint write, and a crash at wave 3 — the supervised session
    must converge to counts bit-identical to the fault-free run, with
    the generation fallback and retry events on the wire."""
    from raft_tpu.obs import Telemetry
    from raft_tpu.obs.events import validate_lines

    model = cached_model(RAFT2)
    inv = _first_inv(model)
    ref = _host_run(model, inv)

    ck = str(tmp_path / "ck.npz")
    mpath = str(tmp_path / "m.jsonl")
    tel = Telemetry(metrics_path=mpath)
    chaos = ChaosInjector(ChaosSpec.parse("ovf=2,crash=3,truncate=2"))
    res = supervise(
        lambda ov: BFSChecker(model, invariants=inv, symmetry=True,
                              chunk=256),
        dict(max_depth=4, checkpoint_path=ck, checkpoint_every_s=0.0,
             chaos=chaos, telemetry=tel),
        backoff_base=0.0, telemetry=tel,
    )
    tel.close()
    assert _sig(res) == _sig(ref)
    assert sorted(chaos.fired) == ["crash", "ovf", "truncate"]
    with open(mpath) as fh:
        counts, problems = validate_lines(fh)
    assert not problems, problems
    assert counts["retry"] == 2 and counts["resume"] == 2
    # the torn generation was skipped on one of the resumes
    assert counts.get("ckpt_generation", 0) >= 1


def test_host_v1_backcompat_resume_zeroes_coverage(tmp_path):
    model = cached_model(RAFT2)
    inv = _first_inv(model)
    ref = _host_run(model, inv)
    ck = str(tmp_path / "ck.npz")
    _host_run(model, inv, checkpoint_path=ck, checkpoint_every_s=0.0,
              max_depth=2)
    # rewrite as a version-1-era file: no format_version, no content
    # hash, no coverage field (pre-coverage builds)
    with np.load(ck, allow_pickle=False) as z:
        fields = {k: z[k] for k in z.files
                  if k not in ("format_version", "content_hash", "coverage")}
    np.savez(ck, **fields)
    res = _host_run(model, inv, resume=ck)
    assert _sig(res)[:5] == _sig(ref)[:5]
    # coverage resumes zeroed: only waves 3..4 are counted
    assert res.coverage is not None
    assert sum(r[2] for r in res.coverage) == ref.distinct - sum(
        ref.depth_counts[:3])


def test_cross_engine_resume_is_a_clear_mismatch(tmp_path):
    """A host checkpoint fed to the device engine must fail on the spec
    identity line — never a numpy KeyError from a missing field."""
    from raft_tpu.checker.device_bfs import DeviceBFS

    model = cached_model(RAFT2)
    inv = _first_inv(model)
    ck = str(tmp_path / "ck.npz")
    _host_run(model, inv, checkpoint_path=ck, checkpoint_every_s=0.0,
              max_depth=2)
    with pytest.raises(ValueError, match="checkpoint is for spec") as ei:
        DeviceBFS(model, invariants=inv).run(resume=ck)
    assert "host/" in str(ei.value) and isinstance(
        ei.value, CheckpointMismatch)


# ------------------------------------------------------- CLI contract


CFG = """\
CONSTANTS
    n1 = n1
    n2 = n2
    v1 = v1
    Server = { n1, n2 }
    Value = { v1 }
    Follower = Follower
    Candidate = Candidate
    Leader = Leader
    Nil = Nil
    RequestVoteRequest = RequestVoteRequest
    RequestVoteResponse = RequestVoteResponse
    AppendEntriesRequest = AppendEntriesRequest
    AppendEntriesResponse = AppendEntriesResponse
    EqualTerm = EqualTerm
    LessOrEqualTerm = LessOrEqualTerm
    MaxElections = 1
    MaxRestarts = 0

INIT Init
NEXT Next

INVARIANT
NoLogDivergence
"""

CLI_BASE = [
    "--platform", "cpu", "--checker", "tpu-host", "--msg-slots", "16",
    "--max-depth", "4", "--chunk", "256",
]


def _cfg(tmp_path):
    cfg = tmp_path / "Raft.cfg"
    cfg.write_text(CFG)
    return str(cfg)


def test_cli_documents_exit_codes():
    import raft_tpu.__main__ as cli

    doc = cli.__doc__
    for needle in ("2 ", "3 ", "4 ", "5 ", "64", "66", "preempted",
                   "unrecoverable"):
        assert needle in doc


def test_cli_chaos_preempt_rc4_and_resume(tmp_path, capsys):
    from raft_tpu.__main__ import main

    cfg = _cfg(tmp_path)
    ck = str(tmp_path / "runs" / "ck.npz")  # exercises --checkpoint makedirs
    rc = main([cfg, *CLI_BASE, "--checkpoint", ck, "--checkpoint-every",
               "0", "--chaos", "preempt=2"])
    cap = capsys.readouterr()
    assert rc == 4, cap.err
    assert "preempted (SIGTERM)" in cap.out and os.path.exists(ck)
    # the preemption checkpoint is hash-verified and resumable
    loaded, gen, skipped = rckpt.load_npz(ck)
    assert gen == 0 and not skipped
    assert rckpt.format_version_of(loaded) == rckpt.FORMAT_VERSION
    rc = main([cfg, *CLI_BASE, "--resume", ck])
    cap = capsys.readouterr()
    assert rc == 0, cap.err
    assert "resume: validated" in cap.err


def test_cli_supervised_chaos_smoke_matches_fault_free(tmp_path, capsys):
    """Fast chaos smoke: crash at wave 2, auto-resume, result line
    identical to the fault-free run."""
    from raft_tpu.__main__ import main

    cfg = _cfg(tmp_path)
    rc = main([cfg, *CLI_BASE])
    ref_line = next(ln for ln in capsys.readouterr().out.splitlines()
                    if ln.startswith("distinct="))
    ck = str(tmp_path / "ck.npz")
    rc = main([cfg, *CLI_BASE, "--checkpoint", ck, "--checkpoint-every",
               "0", "--chaos", "crash=2", "--supervise"])
    cap = capsys.readouterr()
    assert rc == 0, cap.err
    line = next(ln for ln in cap.out.splitlines()
                if ln.startswith("distinct="))
    # wall-clock differs; the counts must not
    assert line.split(" time=")[0] == ref_line.split(" time=")[0]


def test_cli_resume_failfast_exit_codes(tmp_path, capsys):
    from raft_tpu.__main__ import main

    cfg = _cfg(tmp_path)
    # missing file -> 66, before any engine work
    rc = main([cfg, *CLI_BASE, "--resume", str(tmp_path / "none.npz")])
    assert rc == 66
    ck = str(tmp_path / "ck.npz")
    rc = main([cfg, *CLI_BASE, "--checkpoint", ck, "--checkpoint-every",
               "0"])
    assert rc == 0
    # wrong identity (different msg-slots geometry) -> 64 with the spec
    # sentence on stderr
    rc = main([cfg, *CLI_BASE[:-4], "--msg-slots", "24", "--max-depth",
               "4", "--chunk", "256", "--resume", ck])
    cap = capsys.readouterr()
    assert rc == 64 and "checkpoint is for spec" in cap.err
    # every generation torn -> 5 (unrecoverable), problems listed;
    # tearing ONLY the newest would fall back (and exit 0), so tear all
    for g in range(3):
        gp = rckpt.generation_path(ck, g)
        if os.path.exists(gp):
            with open(gp, "r+b") as fh:
                fh.truncate(10)
    rc = main([cfg, *CLI_BASE, "--resume", ck])
    cap = capsys.readouterr()
    assert rc == 5 and "unreadable" in cap.err
    # bad chaos grammar -> 64
    rc = main([cfg, *CLI_BASE, "--chaos", "nope=1"])
    assert rc == 64


def test_cli_sharded_no_reshard_mesh_mismatch_is_exit_64(tmp_path, capsys):
    """Satellite gate: a mesh-size-only mismatch under --no-reshard is a
    usage error (64) whose message names BOTH mesh sizes and the reshard
    path — and it fails fast in validate_resume, before any compile."""
    import jax

    from raft_tpu.__main__ import main
    from raft_tpu.models.raft import RaftParams, cached_model
    from raft_tpu.parallel.sharded import ShardedBFS

    cfg = _cfg(tmp_path)
    model = cached_model(RaftParams(n_servers=2, n_values=1,
                                    max_elections=1, max_restarts=0,
                                    msg_slots=16))
    # symmetry=False: CFG declares no SYMMETRY, and the ident must match
    # the CLI run's exactly except for the /D=n/ component
    eng = ShardedBFS(model, invariants=("NoLogDivergence",), symmetry=False,
                     devices=jax.devices()[:1], chunk=256,
                     frontier_cap=256, seen_cap=1024, journal_cap=1024)
    ident = eng._ckpt_ident()
    assert "/D=1/" in ident
    spec_d2 = ident.replace("/D=1/", "/D=2/")
    assert rckpt.mesh_neutral(spec_d2) == rckpt.mesh_neutral(ident)
    ck = str(tmp_path / "d2.npz")
    rckpt.save_npz(ck, dict(version=2, spec=spec_d2, depth=3))
    rc = main([cfg, "--platform", "cpu", "--checker", "sharded",
               "--devices", "1", "--msg-slots", "16", "--chunk", "256",
               "--no-reshard", "--resume", ck])
    cap = capsys.readouterr()
    assert rc == 64, cap.err
    assert "D=2 mesh" in cap.err and "D=1" in cap.err
    assert "mesh-portable" in cap.err


# ----------------------------------------------- device/sharded (slow)


def _engine_factory(kind, model, inv):
    if kind == "device":
        from raft_tpu.checker.device_bfs import DeviceBFS

        return lambda ov: DeviceBFS(
            model, invariants=inv, symmetry=True,
            **{**dict(chunk=512, frontier_cap=1 << 14, seen_cap=1 << 17,
                      journal_cap=1 << 17), **ov})
    import jax

    from raft_tpu.parallel.sharded import ShardedBFS

    # devices sits in the defaults dict so shard-loss recovery can
    # override it with the survivor list
    return lambda ov: ShardedBFS(
        model, invariants=inv, symmetry=True,
        **{**dict(devices=jax.devices()[:4], chunk=128, frontier_cap=1024,
                  seen_cap=4096), **ov})


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["device", "sharded"])
@pytest.mark.parametrize("family", ["raft", "kraft"])
def test_engine_chaos_parity(kind, family, tmp_path):
    """The chaos parity gate on the accelerator engines: injected
    frontier overflow (wave 2, triggers regrow-and-resume), a torn
    checkpoint write, and a crash at wave 3 — supervised recovery must
    be bit-identical to the fault-free run on both model families."""
    model = cached_model(RAFT2) if family == "raft" else _kraft()
    inv = _first_inv(model)
    factory = _engine_factory(kind, model, inv)
    ref = factory({}).run(max_depth=4)

    ck = str(tmp_path / "ck.npz")
    chaos = ChaosInjector(ChaosSpec.parse("ovf=2,crash=3,truncate=2"))
    res = supervise(
        factory,
        dict(max_depth=4, checkpoint_path=ck, checkpoint_every_s=0.0,
             chaos=chaos),
        backoff_base=0.0,
    )
    assert sorted(chaos.fired) == ["crash", "ovf", "truncate"]
    assert res.distinct == ref.distinct
    assert [int(x) for x in res.depth_counts] == [
        int(x) for x in ref.depth_counts]
    assert res.total == ref.total and res.terminal == ref.terminal
    assert res.coverage == ref.coverage


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["device", "sharded"])
def test_engine_v1_backcompat(kind, tmp_path):
    """A version-1-era checkpoint (no hash, no coverage field) still
    resumes on the accelerator engines, with coverage zeroed."""
    model = cached_model(RAFT2)
    inv = _first_inv(model)
    factory = _engine_factory(kind, model, inv)
    ref = factory({}).run(max_depth=4)
    ck = str(tmp_path / "ck.npz")
    factory({}).run(max_depth=2, checkpoint_path=ck, checkpoint_every_s=0.0)
    with np.load(ck, allow_pickle=False) as z:
        fields = {k: z[k] for k in z.files
                  if k not in ("format_version", "content_hash", "coverage")}
    np.savez(ck, **fields)
    res = factory({}).run(resume=ck, max_depth=4)
    assert res.distinct == ref.distinct
    assert [int(x) for x in res.depth_counts] == [
        int(x) for x in ref.depth_counts]
    assert sum(r[2] for r in res.coverage) == ref.distinct - sum(
        ref.depth_counts[:3])


@pytest.mark.slow
@pytest.mark.parametrize("family", ["raft", "kraft"])
def test_sharded_shard_loss_supervised_parity(family, tmp_path):
    """The elastic-mesh gate: a shard's device dies mid-wave 2 on a D=4
    mesh; the supervisor reshards the spilled wave-start checkpoint onto
    the surviving D=3 mesh and the final counts are bit-identical to the
    fault-free run — on both model families."""
    model = cached_model(RAFT2) if family == "raft" else _kraft()
    inv = _first_inv(model)
    factory = _engine_factory("sharded", model, inv)
    ref = factory({}).run(max_depth=4)

    ck = str(tmp_path / "ck.npz")
    chaos = ChaosInjector(ChaosSpec.parse("shard_loss=2,seed=1"))
    stats: dict = {}
    res = supervise(
        factory,
        dict(max_depth=4, checkpoint_path=ck, checkpoint_every_s=0.0,
             chaos=chaos),
        backoff_base=0.0, stats_out=stats,
    )
    assert chaos.fired == ["shard_loss"]
    assert stats == {"recoveries": 1, "causes": ["shard-lost:1"]}
    assert res.distinct == ref.distinct
    assert [int(x) for x in res.depth_counts] == [
        int(x) for x in ref.depth_counts]
    assert res.total == ref.total and res.terminal == ref.terminal
    # the new-state column's per-action split depends on mesh size (it
    # credits dedup-race winners), so compare the mesh-invariant
    # enabled/fired tallies exactly and the new-state total
    cov_r = np.asarray(ref.coverage)
    cov_n = np.asarray(res.coverage)
    assert (cov_r[:, :2] == cov_n[:, :2]).all()
    assert cov_r[:, 2].sum() == cov_n[:, 2].sum()


@pytest.mark.slow
def test_sharded_stall_watchdog_aborts_with_wave_start_checkpoint(tmp_path):
    """stall_abort_factor=0.0 makes the first eligible wave (the 4th:
    three must be recorded to calibrate the median) trip the watchdog;
    the raise carries a wave-start checkpoint a plain resume completes
    from with zero lost work."""
    from raft_tpu.resilience import ShardStall

    model = cached_model(RAFT2)
    inv = _first_inv(model)
    factory = _engine_factory("sharded", model, inv)
    ref = factory({}).run(max_depth=6)
    ck = str(tmp_path / "ck.npz")
    with pytest.raises(ShardStall) as ei:
        factory({}).run(max_depth=6, checkpoint_path=ck,
                        checkpoint_every_s=1e9, stall_abort_factor=0.0)
    assert ei.value.checkpoint_saved and 0 <= ei.value.shard < 4
    res = factory({}).run(resume=ck, max_depth=6)
    assert res.distinct == ref.distinct
    assert [int(x) for x in res.depth_counts] == [
        int(x) for x in ref.depth_counts]


@pytest.mark.slow
def test_cli_sigterm_device_rc4_checkpoint_resume(tmp_path):
    """The preemptible-TPU drill, end to end: kill -TERM a DeviceBFS
    run mid-flight -> rc 4 with an intact, hash-verified checkpoint ->
    --resume completes cleanly."""
    cfg = _cfg(tmp_path)
    ck = str(tmp_path / "ck.npz")
    base = [sys.executable, "-m", "raft_tpu", cfg, "--platform", "cpu",
            "--checker", "tpu", "--msg-slots", "16", "--max-depth", "6",
            "--chunk", "256", "--checkpoint", ck, "--checkpoint-every", "0"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(base, stderr=subprocess.PIPE, text=True,
                            cwd=os.path.dirname(os.path.dirname(__file__)))
    # wait for the banner (guard installs right after engine build),
    # then one SIGTERM — the run is mid-compile or mid-wave either way
    for line in proc.stderr:
        if line.startswith("spec="):
            break
    time.sleep(1.0)
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=300)
    proc.stderr.close()
    assert rc == 4
    loaded, gen, skipped = rckpt.load_npz(ck)
    assert not skipped
    assert rckpt.format_version_of(loaded) == rckpt.FORMAT_VERSION
    out = subprocess.run(
        base[:-4] + ["--resume", ck], env=env, capture_output=True,
        text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(__file__)))
    assert out.returncode == 0, out.stderr
    assert "no invariant violations" in out.stdout
