"""Fleet checking (raft_tpu/fleet/): manifest parsing, layout grouping,
packed fleet-vs-serial bit-identical parity (counts AND counterexample
traces), the sweep CLI exit codes, and job-tagged telemetry validation.

The parity tests are the fleet analog of the oracle differential: every
job run through the packed config axis must report exactly what a
standalone run of the same constants reports — the fleet_job lane keeps
cross-job fingerprints disjoint and first-occurrence dedup is
fingerprint-value-independent, so any divergence is a packing bug.
"""

import functools
import json
import os

import pytest

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.fleet.cli import sweep_main
from raft_tpu.fleet.driver import SweepOptions, run_sweep
from raft_tpu.fleet.grouping import FLEET_DYN, build_setup, group_jobs
from raft_tpu.fleet.manifest import (
    SIM_DEFAULTS,
    ManifestError,
    cfg_for_job,
    parse_manifest_obj,
)
from raft_tpu.fleet.packer import build_packed
from raft_tpu.models.registry import CfgError, build_from_cfg
from raft_tpu.obs import EVENT_KEYS, Telemetry, validate_lines
from raft_tpu.utils.cfg import Cfg, ModelValue

# The standard parity grid: 4 Raft jobs whose MaxElections/MaxRestarts
# all fit one packed layout (term width bits_for(max_term) agrees).
STD_MANIFEST = {
    "spec": "Raft",
    "defaults": {
        "constants": {
            "Server": ["s1", "s2"],
            "Value": ["v1"],
            "MaxElections": 1,
            "MaxRestarts": 0,
        },
        "invariants": ["LeaderHasAllAckedValues", "NoLogDivergence"],
        "msg_slots": 16,
    },
    "grid": {"MaxElections": [1, 2], "MaxRestarts": [0, 1]},
}
STD_DEPTH = 5


def _mf(obj):
    return parse_manifest_obj(obj, path="<test>")


# ---------------- manifest schema ----------------


def test_grid_cross_product_order_and_names():
    mf = _mf(STD_MANIFEST)
    assert [j.name for j in mf.jobs] == [
        "Raft-MaxElections=1-MaxRestarts=0",
        "Raft-MaxElections=1-MaxRestarts=1",
        "Raft-MaxElections=2-MaxRestarts=0",
        "Raft-MaxElections=2-MaxRestarts=1",
    ]
    j = mf.jobs[1]
    assert j.spec == "Raft"
    assert j.constants["MaxElections"] == 1 and j.constants["MaxRestarts"] == 1
    # defaults merge under the grid point
    assert j.constants["Server"] == ["s1", "s2"]
    assert j.invariants == ("LeaderHasAllAckedValues", "NoLogDivergence")
    assert j.msg_slots == 16 and j.mode == "check" and j.symmetry


def test_explicit_jobs_override_defaults():
    mf = _mf(
        {
            "spec": "Raft",
            "defaults": {
                "constants": {"Server": ["s1", "s2"], "Value": ["v1"],
                              "MaxElections": 1, "MaxRestarts": 0},
                "sim": {"walks": 7},
            },
            "jobs": [
                {"name": "a", "mode": "simulate", "sim": {"seed": 3}},
                {"name": "b", "constants": {"MaxElections": 2},
                 "symmetry": False, "net_faults": True},
            ],
        }
    )
    a, b = mf.jobs
    assert a.mode == "simulate"
    assert a.sim["walks"] == 7 and a.sim["seed"] == 3
    assert a.sim["max_steps"] == SIM_DEFAULTS["max_steps"]
    assert b.constants["MaxElections"] == 2 and not b.symmetry
    assert b.net_faults and not a.net_faults


@pytest.mark.parametrize(
    "obj,msg",
    [
        ({"spec": "Raft"}, "no jobs"),
        ({"grid": {"MaxElections": [1]}}, "missing required key 'spec'"),
        ({"spec": "Raft", "gird": {}}, "unknown manifest keys"),
        ({"spec": "Raft", "grid": {"MaxElections": []}}, "non-empty lists"),
        ({"spec": "Raft", "jobs": [{"name": "a", "mode": "walk"}]}, "mode"),
        ({"spec": "Raft", "jobs": [{"name": "a", "msg_slots": 0}]},
         "msg_slots"),
        ({"spec": "Raft", "jobs": [{"constants": {}}]}, "need a name"),
        ({"spec": "Raft", "jobs": [{"name": "a"}, {"name": "a"}]},
         "duplicate job names"),
        ({"spec": "Raft", "jobs": [{"name": "a", "sim": {"wlks": 1}}]},
         "unknown sim keys"),
        ({"spec": "Raft", "jobs": [{"name": "a",
                                    "constants": {"Server": [1, 2]}}]},
         "constant"),
    ],
)
def test_manifest_errors(obj, msg):
    with pytest.raises(ManifestError, match=msg):
        _mf(obj)


def test_cfg_for_job_lowers_model_values():
    mf = _mf(STD_MANIFEST)
    cfg = cfg_for_job(mf.jobs[0], "m.json")
    assert isinstance(cfg, Cfg)
    assert cfg.path == "m.json#Raft-MaxElections=1-MaxRestarts=0"
    assert cfg.constants["Server"] == (ModelValue("s1"), ModelValue("s2"))
    assert cfg.constants["MaxElections"] == 1
    assert cfg.symmetry is not None  # symmetry defaults on
    no_sym = _mf({"spec": "Raft", "defaults": {"symmetry": False},
                  "jobs": [{"name": "a"}]})
    assert cfg_for_job(no_sym.jobs[0]).symmetry is None


# ---------------- layout grouping ----------------


def test_grouping_shared_term_width_is_one_group():
    """MaxElections 1 and 2 both pack terms in 2 bits: the whole 4-job
    grid compiles once."""
    groups = group_jobs(_mf(STD_MANIFEST))
    assert len(groups) == 1
    (g,) = groups
    assert g.kind == "packed"
    assert g.dyn_consts == ("max_elections", "max_restarts")
    assert g.table.shape == (4, 2)
    assert g.table.tolist() == [[1, 0], [1, 1], [2, 0], [2, 1]]


def test_grouping_splits_on_packer_width():
    """MaxElections 4 needs 3 term bits (max_term 5) — a different
    message layout, so it cannot share the MaxElections<=2 program."""
    obj = dict(STD_MANIFEST, grid={"MaxElections": [1, 2, 4]})
    groups = group_jobs(_mf(obj))
    assert [len(g.jobs) for g in groups] == [2, 1]
    assert all(g.kind == "packed" for g in groups)


def test_grouping_mixed_specs_and_modes():
    obj = {
        "spec": "Raft",
        "defaults": {
            "constants": {"Server": ["s1", "s2"], "Value": ["v1"],
                          "MaxElections": 1, "MaxRestarts": 0},
            "msg_slots": 16,
        },
        "jobs": [
            {"name": "r1"},
            {"name": "r2", "constants": {"MaxElections": 2}},
            {"name": "p1", "spec": "PullRaft", "msg_slots": 24},
            {"name": "sim1", "mode": "simulate"},
        ],
    }
    groups = group_jobs(_mf(obj))
    kinds = [(g.kind, [j.name for j in g.jobs]) for g in groups]
    assert kinds == [
        ("packed", ["r1", "r2"]),
        ("packed", ["p1"]),
        ("simulate", ["sim1"]),
    ]
    assert "PullRaftParams" in FLEET_DYN  # p1 rides the packed path too


# ---------------- packed fleet vs serial: bit-identical parity ----------


# Tier-1 keeps a 2-job gate (3 compiles total); the full 4-job grid and
# the device queue arm ride the slow set with the other exhaustive
# host/device parity tests.
SM_MANIFEST = dict(STD_MANIFEST, grid={"MaxElections": [1, 2]})


@functools.lru_cache(maxsize=None)
def _serial_ref(which: str):
    """Serial reference for a grid: one standalone checker per job,
    fresh model each (what N separate CLI runs would do)."""
    mf = _mf(STD_MANIFEST if which == "std" else SM_MANIFEST)
    out = {}
    for job in mf.jobs:
        setup = build_setup(job, mf.path)
        res = BFSChecker(
            setup.model, invariants=setup.invariants,
            symmetry=setup.symmetry, chunk=512,
        ).run(max_depth=STD_DEPTH)
        out[job.name] = res
    return out


def test_fleet_host_coresident_parity():
    mf = _mf(SM_MANIFEST)
    (group,) = group_jobs(mf)
    model = build_packed(group)
    setup = group.setups[0]
    names = [j.name for j in group.jobs]
    results = BFSChecker(
        model, invariants=setup.invariants, symmetry=setup.symmetry,
        chunk=512,
    ).run_fleet(job_names=names, max_depth=STD_DEPTH)
    serial = _serial_ref("sm")
    assert len(results) == len(names)
    for name, r in zip(names, results):
        s = serial[name]
        assert r.violation is None and s.violation is None
        assert (r.distinct, r.total, r.depth, r.terminal) == (
            s.distinct, s.total, s.depth, s.terminal), name
        assert r.depth_counts == s.depth_counts, name
        # the shared-wave bincount split must reproduce per-job coverage
        assert r.coverage == s.coverage, name


@pytest.mark.slow
def test_fleet_device_queue_parity():
    """tpu engine queue arm: same packed model, jobs run back-to-back
    through one jit cache; counts must match the serial host runs."""
    mf = _mf(STD_MANIFEST)
    tel = Telemetry()
    res = run_sweep(
        mf, SweepOptions(engine="tpu", max_depth=STD_DEPTH, chunk=512),
        telemetry=tel,
    )
    assert res.rc == 0
    assert res.amortization == {
        "jobs": 4, "groups": 1, "precompiles": 1, "precompile_ratio": 0.25,
    }
    serial = _serial_ref("std")
    for jr in res.jobs:
        s = serial[jr.name]
        assert (jr.distinct, jr.total, jr.depth, jr.terminal) == (
            s.distinct, s.total, s.depth, s.terminal), jr.name
        assert jr.rc == 0
        assert jr.exit_cause in ("max_depth", "exhausted")
    # one multiplexed stream: schema-clean, one manifest+summary per job
    lines = [json.dumps(e) for e in tel.events]
    counts, problems = validate_lines(lines)
    assert problems == []
    tagged = {e.get("job") for e in tel.events if e.get("job")}
    assert tagged == {j.name for j in mf.jobs}
    for name in tagged:
        evs = [e for e in tel.events if e.get("job") == name]
        assert [e["event"] for e in evs].count("manifest") == 1
        assert [e["event"] for e in evs].count("summary") == 1


def _strip_fleet(dec: dict) -> dict:
    return {
        k: v for k, v in dec.items()
        if k != "fleet_job" and not k.startswith("c_")
    }


@pytest.mark.slow
def test_fleet_violation_trace_parity():
    """A job that violates mid-sweep must report the SAME shortest
    counterexample as its standalone run — action labels and decoded
    states (modulo the packed model's extra config lanes)."""
    obj = {
        "spec": "FlexibleRaft",
        "defaults": {
            "constants": {
                "Server": ["s1", "s2"], "Value": ["v1"],
                "MaxRestarts": 0, "ElectionQuorumSize": 1,
                "ReplicationQuorumSize": 1,
            },
            "invariants": ["LeaderHasAllAckedValues"],
            "msg_slots": 24,
        },
        "grid": {"MaxElections": [1, 2]},
    }
    mf = _mf(obj)
    (group,) = group_jobs(mf)  # one packed group despite the violation
    model = build_packed(group)
    setup = group.setups[0]
    names = [j.name for j in group.jobs]
    fleet = BFSChecker(
        model, invariants=setup.invariants, symmetry=setup.symmetry,
        chunk=512,
    ).run_fleet(job_names=names)
    serial = {}
    for job in mf.jobs:
        s = build_setup(job, mf.path)
        serial[job.name] = BFSChecker(
            s.model, invariants=s.invariants, symmetry=s.symmetry, chunk=512,
        ).run()
    clean, bad = fleet
    # ME=1: single-vote election quorum cannot lose an ack yet — exhausts
    sref = serial[names[0]]
    assert clean.violation is None and sref.violation is None
    assert clean.exhausted and clean.distinct == sref.distinct
    assert clean.depth_counts == sref.depth_counts
    # ME=2: the flexible quorums violate LeaderHasAllAckedValues
    bref = serial[names[1]]
    assert bad.violation is not None and bref.violation is not None
    assert bad.violation.invariant == bref.violation.invariant
    assert bad.violation.depth == bref.violation.depth
    assert [a for a, _ in bad.trace] == [a for a, _ in bref.trace]
    for (_, fdec), (_, sdec) in zip(bad.trace, bref.trace):
        assert _strip_fleet(fdec) == sdec


@pytest.mark.slow
def test_fleet_pull_raft_family_parity():
    """Second packable family (PullRaftParams): host co-resident AND
    device queue arms must both match standalone runs."""
    obj = {
        "spec": "PullRaft",
        "defaults": {
            "constants": {"Server": ["s1", "s2"], "Value": ["v1"],
                          "MaxElections": 1, "MaxRestarts": 1},
            "invariants": ["NoLogDivergence", "LeaderHasAllAckedValues"],
            "msg_slots": 24,
        },
        "grid": {"MaxElections": [1, 2]},
    }
    mf = _mf(obj)
    (group,) = group_jobs(mf)
    assert group.kind == "packed" and group.dyn_consts == ("max_elections",)
    serial = {}
    for job in mf.jobs:
        s = build_setup(job, mf.path)
        serial[job.name] = BFSChecker(
            s.model, invariants=s.invariants, symmetry=s.symmetry, chunk=512,
        ).run(max_depth=STD_DEPTH)
    for engine in ("host", "tpu"):
        res = run_sweep(mf, SweepOptions(
            engine=engine, max_depth=STD_DEPTH, chunk=512,
        ))
        assert res.rc == 0 and res.precompiles == 1
        for jr in res.jobs:
            s = serial[jr.name]
            assert (jr.distinct, jr.total, jr.depth, jr.terminal) == (
                s.distinct, s.total, s.depth, s.terminal), (engine, jr.name)


def test_rc_mapping():
    from raft_tpu.fleet.results import FleetResult, JobResult, rc_for

    assert rc_for("exhausted", None) == 0
    assert rc_for("max_depth", None) == 0
    assert rc_for("violation", {"invariant": "NoLogDivergence"}) == 2
    assert rc_for("preempted", None) == 4
    assert rc_for("unrecoverable", None) == 5
    jobs = [
        JobResult(name="a", mode="check", rc=0, seconds=0.0),
        JobResult(name="b", mode="check", rc=2, seconds=0.0),
    ]
    fr = FleetResult(jobs=jobs, groups=1, precompiles=1, seconds=0.0)
    assert fr.rc == 2  # worst job wins
    assert fr.to_json()["jobs"][1]["rc"] == 2


# ---------------- sweep driver + resume ----------------


def test_run_sweep_host_and_resume(tmp_path):
    mf = _mf(SM_MANIFEST)
    opts = SweepOptions(
        engine="host", max_depth=STD_DEPTH, chunk=512,
        state_dir=str(tmp_path),
    )
    res = run_sweep(mf, opts)
    assert res.rc == 0 and res.groups == 1 and res.precompiles == 1
    serial = _serial_ref("sm")
    for jr in res.jobs:
        assert jr.distinct == serial[jr.name].distinct, jr.name
    state = json.loads((tmp_path / "fleet_state.json").read_text())
    assert state["completed"] == {j.name: 0 for j in mf.jobs}
    # resume: every job already completed -> nothing recompiles or reruns
    res2 = run_sweep(mf, SweepOptions(
        engine="host", max_depth=STD_DEPTH, chunk=512,
        state_dir=str(tmp_path), resume=True,
    ))
    assert res2.precompiles == 0
    assert all(j.skipped and j.rc == 0 for j in res2.jobs)


def test_fleet_lineage_names_do_not_collide(tmp_path):
    """Regression: job names that sanitize to the same string ("a/b" and
    "a_b") used to alias one checkpoint lineage; the job-index suffix
    keeps them distinct on disk."""
    from raft_tpu.checker.device_bfs import DeviceBFS
    from raft_tpu.resilience import lineage_name

    obj = {
        "spec": "Raft",
        "defaults": dict(STD_MANIFEST["defaults"]),
        "jobs": [{"name": "a/b"}, {"name": "a_b"}],
    }
    mf = _mf(obj)
    (group,) = group_jobs(mf)
    model = build_packed(group)
    setup = group.setups[0]
    names = [j.name for j in group.jobs]
    ckdir = str(tmp_path / "ckpt")
    eng = DeviceBFS(model, invariants=setup.invariants,
                    symmetry=setup.symmetry, chunk=256,
                    frontier_cap=1 << 12, seen_cap=1 << 15,
                    journal_cap=1 << 15)
    eng.run_fleet(job_names=names, checkpoint_dir=ckdir,
                  checkpoint_every_s=0.0, max_depth=1)
    files = sorted(os.listdir(ckdir))
    lineages = [f for f in files if f.endswith(".ckpt.npz")]
    assert lineage_name("a/b", 0) in lineages
    assert lineage_name("a_b", 1) in lineages
    assert len({lineage_name(n, i) for i, n in enumerate(names)}) == 2


@pytest.mark.slow
def test_run_sweep_supervised_recovers_and_records(tmp_path):
    """Supervised sweep: a job with injected chaos (crash at wave 2)
    recovers inside its budget, reports rc 0 with its recovery count in
    the JobResult JSON and fleet_state.json; with budget 0 the same
    fault becomes an rc-5 unrecoverable result that does NOT kill the
    other job."""
    # names match SM_MANIFEST's grid auto-names so _serial_ref("sm")
    # provides the fault-free parity references
    names = ["Raft-MaxElections=1", "Raft-MaxElections=2"]
    obj = {
        "spec": "Raft",
        "defaults": dict(STD_MANIFEST["defaults"]),
        "jobs": [
            {"name": names[0]},
            {"name": names[1], "constants": {"MaxElections": 2},
             "chaos": "crash=2"},
        ],
    }
    serial = _serial_ref("sm")
    res = run_sweep(_mf(obj), SweepOptions(
        engine="tpu", max_depth=STD_DEPTH, chunk=512,
        state_dir=str(tmp_path / "s1"), supervise=2,
    ))
    assert res.rc == 0
    by_name = {j.name: j for j in res.jobs}
    assert by_name[names[0]].recoveries == 0
    assert by_name[names[1]].recoveries == 1
    assert by_name[names[1]].to_json()["recoveries"] == 1
    # recovery is exploration-neutral: counts match the serial refs
    for n in names:
        assert by_name[n].distinct == serial[n].distinct, n
    state = json.loads(
        (tmp_path / "s1" / "fleet_state.json").read_text())
    assert state["completed"] == {n: 0 for n in names}
    assert state["recoveries"][names[1]] == 1
    # budget 0: the crash is terminal for its job only
    res = run_sweep(_mf(obj), SweepOptions(
        engine="tpu", max_depth=STD_DEPTH, chunk=512,
        state_dir=str(tmp_path / "s2"), supervise=0,
    ))
    assert res.rc == 5
    by_name = {j.name: j for j in res.jobs}
    assert by_name[names[0]].rc == 0
    assert by_name[names[1]].rc == 5
    assert by_name[names[1]].exit_cause == "unrecoverable"


@pytest.mark.slow
def test_fleet_supervised_8_jobs_one_crashing_twice(tmp_path, monkeypatch):
    """The acceptance sweep: 8 jobs, one suffering two injected faults;
    everything finishes rc 0, the recovery count is recorded, and NO
    recovery triggered an engine rebuild (empty-override recoveries ride
    the group's compiled programs — zero recompiles)."""
    from raft_tpu.checker.device_bfs import DeviceBFS

    grid_jobs = [
        {"name": f"g-ME={me}-MR={mr}",
         "constants": {"MaxElections": me, "MaxRestarts": mr}}
        for me in (1, 2) for mr in (0, 1)
    ]
    twin_jobs = [dict(j, name=j["name"].replace("g-", "t-"))
                 for j in grid_jobs]
    # one twin crashes at wave 2 and flakes at wave 3: two recoveries
    twin_jobs[2]["chaos"] = "crash=2,transient=3"
    obj = {"spec": "Raft", "defaults": dict(STD_MANIFEST["defaults"]),
           "jobs": grid_jobs + twin_jobs}
    mf = _mf(obj)
    assert len(mf.jobs) == 8

    def no_rebuild(self, overrides):
        raise AssertionError(
            f"recovery caused an engine rebuild: {overrides}")

    monkeypatch.setattr(DeviceBFS, "_rebuild", no_rebuild)
    res = run_sweep(mf, SweepOptions(
        engine="tpu", max_depth=STD_DEPTH, chunk=512,
        state_dir=str(tmp_path), supervise=5,
    ))
    assert res.rc == 0
    assert all(j.rc == 0 for j in res.jobs)
    by_name = {j.name: j for j in res.jobs}
    crashed = twin_jobs[2]["name"]
    assert by_name[crashed].recoveries == 2
    # the chaos job's counts equal its fault-free twin's
    twin = crashed.replace("t-", "g-")
    assert by_name[crashed].distinct == by_name[twin].distinct
    assert by_name[crashed].total == by_name[twin].total
    state = json.loads((tmp_path / "fleet_state.json").read_text())
    assert state["recoveries"][crashed] == 2
    assert all(v == 0 for n, v in state["recoveries"].items()
               if n != crashed)


def test_run_sweep_jobs_glob():
    mf = _mf(STD_MANIFEST)
    res = run_sweep(mf, SweepOptions(
        engine="host", max_depth=3, chunk=512,
        jobs_glob="*MaxElections=1*",
    ))
    assert [j.name for j in res.jobs] == [
        "Raft-MaxElections=1-MaxRestarts=0",
        "Raft-MaxElections=1-MaxRestarts=1",
    ]
    with pytest.raises(ManifestError, match="matches none"):
        run_sweep(mf, SweepOptions(jobs_glob="nope-*"))


# ---------------- CLI exit codes ----------------


def test_sweep_cli_json_roundtrip(tmp_path, capsys):
    path = tmp_path / "m.json"
    path.write_text(json.dumps(STD_MANIFEST))
    rc = sweep_main([str(path), "--max-depth", "3", "--json",
                     "--jobs", "*MaxRestarts=0*"])
    assert rc == 0
    lines = [json.loads(x) for x in capsys.readouterr().out.splitlines() if x]
    assert [x["job"] for x in lines[:-1]] == [
        "Raft-MaxElections=1-MaxRestarts=0",
        "Raft-MaxElections=2-MaxRestarts=0",
    ]
    agg = lines[-1]
    assert agg["fleet"] is True and agg["rc"] == 0
    assert agg["amortization"]["precompiles"] == 1


def test_sweep_cli_usage_errors(tmp_path):
    missing = tmp_path / "nope.json"
    assert sweep_main([str(missing)]) == 66
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert sweep_main([str(bad)]) == 64
    unknown = tmp_path / "unknown.json"
    unknown.write_text(json.dumps({
        "spec": "Bogus",
        "jobs": [{"name": "a", "constants": {
            "Server": ["s1"], "Value": ["v1"],
            "MaxElections": 1, "MaxRestarts": 0}}],
    }))
    assert sweep_main([str(unknown)]) == 64
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(STD_MANIFEST))
    # --resume without a --state-dir to resume from is a usage error
    assert sweep_main([str(ok), "--resume"]) == 64


def test_build_from_cfg_unknown_spec_diagnostic():
    cfg = Cfg(path="x.cfg", constants={}, symmetry=None, invariants=[],
              model_values=[])
    with pytest.raises(CfgError) as ei:
        build_from_cfg(cfg, spec="Bogus")
    msg = str(ei.value)
    assert "no TPU lowering registered for spec 'Bogus'" in msg
    # the diagnostic must enumerate what IS available
    for name in ("Raft", "PullRaft", "KRaftWithReconfig"):
        assert name in msg


# ---------------- job-tagged stream validation ----------------


def _ev(etype, **extra):
    ev = dict.fromkeys(EVENT_KEYS[etype])
    ev["event"] = etype
    if etype == "summary":
        ev["exit_cause"] = "exhausted"
    if etype == "wave":
        ev["wave"] = 1
    if etype == "coverage":
        ev.update(actions=[], actions_total=0, wave=0)
    ev.update(extra)
    return json.dumps(ev)


def test_validate_lines_accepts_multiplexed_jobs():
    lines = [
        _ev("manifest", job="a"), _ev("wave", wave=1, job="a"),
        _ev("summary", job="a"),
        _ev("manifest", job="b"), _ev("wave", wave=1, job="b"),
        _ev("summary", job="b"),
    ]
    counts, problems = validate_lines(lines)
    assert problems == []
    assert counts["manifest"] == counts["summary"] == 2


def test_validate_lines_flags_per_job_wave_regression():
    # job a's second run re-emits wave 1 without a new job-a manifest:
    # legal globally (the job-b manifest reset the stream counter) but
    # a per-job monotonicity break
    lines = [
        _ev("manifest", job="a"), _ev("wave", wave=1, job="a"),
        _ev("summary", job="a"),
        _ev("manifest", job="b"), _ev("wave", wave=1, job="a"),
        _ev("summary", job="b"),
    ]
    _, problems = validate_lines(lines)
    assert any("job 'a' wave index 1" in p for p in problems)


def test_validate_lines_flags_missing_job_summary():
    lines = [_ev("manifest", job="a"), _ev("wave", wave=1, job="a")]
    _, problems = validate_lines(lines)
    assert any("1 manifest(s) but 0" in p for p in problems)


def test_validate_lines_flags_bad_job_tag():
    _, problems = validate_lines([_ev("manifest", job="")])
    assert any("non-empty string" in p for p in problems)
