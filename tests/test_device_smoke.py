"""Fast-suite device-kernel smoke, one per spec family (round-4 verdict
Next #6: the default run previously exercised almost no device kernels —
everything differential was slow-marked, so a broken action kernel in a
non-core family would sail through the default suite).

Each smoke is a successor-set differential on a shallow reachable sample
(depth 2-3, tiny batch): the device `expand` (every action kernel, the
bag writes, the packed encodings) must produce EXACTLY the oracle's
successor multiset for every sampled state. Any kernel mutation that
changes behavior on the first few levels fails here; the deep/bounded
differentials stay in the slow suite.
"""

import jax
import jax.numpy as jnp
import numpy as np

from conftest import collect_states

DEPTH, CAP = 3, 48


def _successor_smoke(model, oracle, multiset=True):
    states = collect_states(oracle, max_depth=DEPTH, cap=CAP)
    vecs = np.stack([model.encode(st) for st in states])
    succs, valid, _rank, ovf = jax.device_get(model.expand(jnp.asarray(vecs)))
    assert not (np.asarray(valid) & np.asarray(ovf)).any()
    for b, st in enumerate(states):
        if multiset:
            got = sorted(
                oracle.serialize_full(model.decode(succs[b, a]))
                for a in np.nonzero(valid[b])[0]
            )
            want = sorted(
                oracle.serialize_full(s2) for _l, s2 in oracle.successors(st)
            )
        else:
            got = {
                oracle.serialize_full(model.decode(succs[b, a]))
                for a in np.nonzero(valid[b])[0]
            }
            want = {
                oracle.serialize_full(s2) for _l, s2 in oracle.successors(st)
            }
        assert got == want, f"state {b}: device/oracle successor mismatch"


def test_smoke_fsync():
    from raft_tpu.models.raft import RaftParams, cached_model
    from raft_tpu.oracle.raft_oracle import oracle_for

    p = RaftParams(
        n_servers=3, n_values=1, max_elections=1, max_restarts=1,
        msg_slots=24, strict_send_once=True, has_pending_response=False,
        trunc_term_mismatch=True, has_fsync=True,
        fsync_leader_before_ae=False, fsync_leader_quorum=True,
        fsync_follower_reply=True,
    )
    _successor_smoke(cached_model(p), oracle_for(p))


def test_smoke_flexible():
    from raft_tpu.models.raft import RaftParams, cached_model
    from raft_tpu.oracle.raft_oracle import oracle_for

    p = RaftParams(
        n_servers=3, n_values=1, max_elections=2, max_restarts=0,
        msg_slots=24, election_quorum=2, replication_quorum=3,
    )
    _successor_smoke(cached_model(p), oracle_for(p))


def test_smoke_pull_raft_and_variant2():
    from raft_tpu.models.pull_raft import PullRaftParams, cached_model
    from raft_tpu.oracle.pull_oracle import PullRaftOracle

    for v2 in (False, True):
        p = PullRaftParams(
            n_servers=3, n_values=1, max_elections=2, max_restarts=0,
            msg_slots=24, variant2=v2,
        )
        o = PullRaftOracle(
            p.n_servers, p.n_values, p.max_elections, p.max_restarts,
            variant2=v2,
        )
        _successor_smoke(cached_model(p), o)


def test_smoke_kraft():
    from raft_tpu.models.kraft import KRaftParams, cached_model
    from raft_tpu.oracle.kraft_oracle import KRaftOracle

    p = KRaftParams(n_servers=3, n_values=1, max_elections=2,
                    max_restarts=0, msg_slots=24)
    o = KRaftOracle(p.n_servers, p.n_values, p.max_elections, p.max_restarts)
    _successor_smoke(cached_model(p), o)


def test_smoke_joint_reconfig():
    from raft_tpu.models.joint_raft import JointRaftParams, cached_model
    from raft_tpu.oracle.joint_oracle import JointRaftOracle

    p = JointRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=1,
        max_restarts=0, max_reconfigs=1, max_values_per_term=1,
        reconfig_type=2, msg_slots=64,
    )
    o = JointRaftOracle(
        p.n_servers, p.n_values, p.init_cluster_size, p.max_elections,
        p.max_restarts, p.max_reconfigs, p.max_values_per_term,
        p.reconfig_type,
    )
    _successor_smoke(cached_model(p), o)


def test_smoke_add_remove_reconfig():
    from raft_tpu.models.reconfig_raft import ReconfigRaftParams, cached_model
    from raft_tpu.oracle.reconfig_oracle import ReconfigRaftOracle

    p = ReconfigRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=1,
        max_restarts=0, max_values_per_term=1, max_add_reconfigs=1,
        max_remove_reconfigs=1, min_cluster_size=2, max_cluster_size=3,
        msg_slots=64,
    )
    o = ReconfigRaftOracle(
        p.n_servers, p.n_values, p.init_cluster_size, p.max_elections,
        p.max_restarts, p.max_values_per_term, p.max_add_reconfigs,
        p.max_remove_reconfigs, p.min_cluster_size, p.max_cluster_size,
        include_thesis_bug=p.include_thesis_bug,
    )
    _successor_smoke(cached_model(p), o)


def test_smoke_kraft_reconfig():
    from raft_tpu.models.kraft_reconfig import KRaftReconfigParams, cached_model
    from raft_tpu.oracle.kraft_reconfig_oracle import KRaftReconfigOracle

    p = KRaftReconfigParams(
        n_hosts=3, n_values=1, init_cluster_size=2, min_cluster_size=2,
        max_cluster_size=3, max_elections=1, max_restarts=1,
        max_values_per_epoch=1, max_add_reconfigs=1, max_remove_reconfigs=1,
        max_spawned_servers=4, msg_slots=24,
    )
    o = KRaftReconfigOracle(
        n_hosts=3, n_values=1, init_cluster_size=2, min_cluster_size=2,
        max_cluster_size=3, max_elections=1, max_restarts=1,
        max_values_per_epoch=1, max_add_reconfigs=1, max_remove_reconfigs=1,
        max_spawned_servers=4,
    )
    # duplicate candidate bindings can yield the same successor in the
    # slot encoding; set equality mirrors the family's slow differential
    _successor_smoke(cached_model(p), o, multiset=False)
