"""KRaft differential tests: the TPU kernels vs the independent oracle
interpreter (pull-raft/KRaft.tla, 961 lines), BFS count parity,
transition-machine unit cases, and reference-cfg loading."""

import numpy as np
import pytest

from pathlib import Path

import jax

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.models.kraft import KRaftModel, KRaftParams, cached_model
from raft_tpu.oracle.kraft_oracle import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    UNATTACHED,
    KRaftOracle,
    end_offset_for_epoch,
    highest_common_offset,
)

from conftest import collect_states as _collect_states


def oracle_for(p: KRaftParams) -> KRaftOracle:
    return KRaftOracle(p.n_servers, p.n_values, p.max_elections, p.max_restarts)


PARAMS = [
    KRaftParams(n_servers=3, n_values=1, max_elections=2, max_restarts=0,
                msg_slots=56),
    KRaftParams(n_servers=3, n_values=2, max_elections=2, max_restarts=1,
                msg_slots=64),
]


@pytest.mark.parametrize("params", PARAMS)
def test_successor_sets_match_oracle(params):
    model = cached_model(params)
    oracle = oracle_for(params)
    states = _collect_states(oracle, max_depth=8, cap=140)
    vecs = np.stack([model.encode(st) for st in states])
    succs, valid, rank, ovf = jax.device_get(model.expand(vecs))
    assert not np.any(valid & ovf)
    for b, st in enumerate(states):
        got = sorted(
            oracle.serialize_full(model.decode(succs[b, a]))
            for a in range(model.A)
            if valid[b, a]
        )
        want = sorted(oracle.serialize_full(s2) for _l, s2 in oracle.successors(st))
        assert got == want, f"successor mismatch at state {b}"


def test_encode_decode_roundtrip():
    params = PARAMS[0]
    model = cached_model(params)
    oracle = oracle_for(params)
    for st in _collect_states(oracle, max_depth=7, cap=120):
        assert model.decode(model.encode(st)) == st


@pytest.mark.slow
def test_bfs_counts_match_oracle():
    params = KRaftParams(
        n_servers=3, n_values=1, max_elections=1, max_restarts=0, msg_slots=40
    )
    model = cached_model(params)
    oracle = oracle_for(params)
    invs = (
        "LeaderHasAllAckedValues",
        "NoLogDivergence",
        "NeverTwoLeadersInSameEpoch",
        "NoIllegalState",
    )
    checker = BFSChecker(model, invariants=invs, symmetry=True, chunk=256)
    res = checker.run(max_depth=10)
    ores = oracle.bfs(invariants=invs, symmetry=True, max_depth=10)
    assert res.violation is None and ores["violation"] is None
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]
    assert res.total == ores["total"]


def test_log_position_math_matches_reference_cases():
    """EndOffsetForEpoch (KRaft.tla:285-301) and HighestCommonOffset
    (KRaft.tla:255-273) on hand-checked logs."""
    # log epochs: [1, 1, 2, 4]
    log = ((1, 0), (1, 1), (2, 0), (4, 1))
    assert end_offset_for_epoch(log, 4) == (4, 4)
    assert end_offset_for_epoch(log, 3) == (3, 2)  # highest entry epoch <= 3
    assert end_offset_for_epoch(log, 1) == (2, 1)
    assert end_offset_for_epoch(log, 0) == (0, 0)
    assert end_offset_for_epoch((), 5) == (0, 0)
    # CompareEntries order: epoch precedence, then offset
    assert highest_common_offset(log, 3, 2) == (3, 2)
    assert highest_common_offset(log, 9, 1) == (2, 1)  # epoch cap beats offset
    assert highest_common_offset(log, 0, 0) == (0, 0)
    assert highest_common_offset((), 3, 2) == (0, 0)


def test_transition_machine_cases():
    """MaybeTransition/MaybeHandleCommonResponse (KRaft.tla:351-392) corner
    cases via the oracle helpers."""
    o = KRaftOracle(3, 1, 2, 0)
    st = o.init_state()
    # Unattached node learns of higher epoch with no leader id -> Unattached
    new = o._maybe_transition(st, 0, None, 2)
    assert new == {"state": UNATTACHED, "epoch": 2, "leader": None}
    # ... with a leader id -> Follower
    new = o._maybe_transition(st, 0, 1, 2)
    assert new == {"state": FOLLOWER, "epoch": 2, "leader": 1}
    # equal epoch, known other leader, conflicting leader id -> IllegalState
    st2 = o._with(
        st,
        leader=(1, None, None),
        state=(FOLLOWER, UNATTACHED, UNATTACHED),
    )
    new = o._maybe_transition(st2, 0, 2, 1)
    assert new["state"] == 5  # ILLEGAL
    # a peer claiming I am leader when I am not -> inconsistent -> Illegal
    new = o._maybe_transition(st, 0, 0, 1)
    assert new["state"] == 5
    # stale epoch response is handled as a no-op
    st3 = o._with(st, currentEpoch=(3, 1, 1))
    new = o._maybe_handle_common_response(st3, 0, None, 1, None)
    assert new["handled"] and new["state"] == st3["state"][0]


def test_kraft_flow_reaches_commit():
    """End-to-end protocol sanity: election -> BeginQuorum -> fetch loop ->
    high-watermark advance -> ack."""
    params = KRaftParams(n_servers=3, n_values=1, max_elections=1,
                         max_restarts=0, msg_slots=40)
    oracle = oracle_for(params)
    st = oracle.init_state()

    def step(label_prefix):
        nonlocal st
        for label, s2 in oracle.successors(st):
            if label.startswith(label_prefix):
                st = s2
                return
        raise AssertionError(f"no successor matching {label_prefix!r}")

    step("RequestVote(0)")
    step("HandleRequestVoteRequest")  # an Unattached peer votes
    step("HandleRequestVoteResponse")
    step("BecomeLeader(0)")
    step("HandleBeginQuorumRequest")  # a peer becomes follower of 0
    step("ClientRequest(0,0)")
    step("SendFetchRequest")
    step("AcceptFetchRequest")  # offset 0 registered; ships entry 1
    step("HandleSuccessFetchResponse")
    step("SendFetchRequest")  # now at offset 1
    step("AcceptFetchRequest")  # endOffset=1 -> quorum -> hwm 1
    assert st["highWatermark"][0] == 1
    assert st["acked"][0] is True
    assert oracle.no_log_divergence(st)
    assert oracle.never_two_leaders_in_same_epoch(st)


def test_fetch_response_no_duplicate_rule():
    """Reply refuses to duplicate a FetchResponse (KRaft.tla:220-227): an
    identical empty fetch response blocks a second identical reply."""
    params = KRaftParams(n_servers=3, n_values=1, max_elections=1,
                         max_restarts=0, msg_slots=40)
    oracle = oracle_for(params)
    st = oracle.init_state()

    def step(prefix):
        nonlocal st
        for label, s2 in oracle.successors(st):
            if label.startswith(prefix):
                st = s2
                return True
        return False

    assert step("RequestVote(0)")
    assert step("HandleRequestVoteRequest")
    assert step("HandleRequestVoteResponse")
    assert step("BecomeLeader(0)")
    assert step("HandleBeginQuorumRequest")
    assert step("SendFetchRequest")
    assert step("AcceptFetchRequest")  # empty response (no entries)
    # the identical fetch request is re-sendable after response handling;
    # here the response is still in flight, leader cannot answer again
    # (fetch request count is 0 after the Reply discard, so no re-accept)
    assert not any(
        l.startswith("AcceptFetchRequest") for l, _ in oracle.successors(st)
    )


@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_reference_kraft_cfg_loads():
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    cfg = parse_cfg("/root/reference/specifications/pull-raft/KRaft.cfg")
    setup = build_from_cfg(cfg, msg_slots=48)
    assert setup.model.name == "KRaft"
    assert setup.model.p.n_servers == 3
    assert setup.model.p.n_values == 1
    assert setup.model.p.max_elections == 2
    assert setup.invariants == (
        "LeaderHasAllAckedValues",
        "NoLogDivergence",
        "NeverTwoLeadersInSameEpoch",
        "NoIllegalState",
    )
    assert setup.symmetry
