"""PullRaft / PullRaftVariant2 differential tests: the TPU kernels vs the
independent oracle interpreter (pull-raft/PullRaft.tla, 631 lines;
PullRaftVariant2.tla, 648 lines), BFS count parity, reference-cfg loading
with the documented `v2` diagnosis (PullRaft.cfg:9-11)."""

import numpy as np
import pytest

from pathlib import Path

import jax

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.models.pull_raft import PullRaftModel, PullRaftParams, cached_model
from raft_tpu.oracle.pull_oracle import PullRaftOracle, last_common_entry

from conftest import collect_states as _collect_states


def oracle_for(p: PullRaftParams) -> PullRaftOracle:
    return PullRaftOracle(
        p.n_servers, p.n_values, p.max_elections, p.max_restarts, variant2=p.variant2
    )


PARAMS = [
    PullRaftParams(n_servers=3, n_values=1, max_elections=2, max_restarts=0,
                   msg_slots=40),
    PullRaftParams(n_servers=3, n_values=1, max_elections=2, max_restarts=0,
                   msg_slots=40, variant2=True),
    PullRaftParams(n_servers=3, n_values=2, max_elections=2, max_restarts=1,
                   msg_slots=48, variant2=True),
]


@pytest.mark.parametrize("params", PARAMS)
def test_successor_sets_match_oracle(params):
    model = cached_model(params)
    oracle = oracle_for(params)
    states = _collect_states(oracle, max_depth=7, cap=140)
    vecs = np.stack([model.encode(st) for st in states])
    succs, valid, rank, ovf = jax.device_get(model.expand(vecs))
    assert not np.any(valid & ovf)
    for b, st in enumerate(states):
        got = sorted(
            oracle.serialize_full(model.decode(succs[b, a]))
            for a in range(model.A)
            if valid[b, a]
        )
        want = sorted(oracle.serialize_full(s2) for _l, s2 in oracle.successors(st))
        assert got == want, f"successor mismatch at state {b} ({model.name})"


@pytest.mark.parametrize("params", PARAMS[:2])
def test_encode_decode_roundtrip(params):
    model = cached_model(params)
    oracle = oracle_for(params)
    for st in _collect_states(oracle, max_depth=6, cap=100):
        assert model.decode(model.encode(st)) == st


@pytest.mark.slow
@pytest.mark.parametrize("variant2", [False, True])
def test_bfs_counts_match_oracle(variant2):
    params = PullRaftParams(
        n_servers=3, n_values=1, max_elections=1, max_restarts=0, msg_slots=32,
        variant2=variant2,
    )
    model = cached_model(params)
    oracle = oracle_for(params)
    invs = ("LeaderHasAllAckedValues", "NoLogDivergence")
    checker = BFSChecker(model, invariants=invs, symmetry=True, chunk=256)
    res = checker.run(max_depth=10)
    ores = oracle.bfs(invariants=invs, symmetry=True, max_depth=10)
    assert res.violation is None and ores["violation"] is None
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]
    assert res.total == ores["total"]


def test_last_common_entry_matches_reference_cases():
    """LastCommonEntry (PullRaft.tla:211-226): term precedence, index
    tiebreak, empty-log and no-common cases."""
    # leader log: terms [1, 1, 2, 3]
    log = ((1, 0), (1, 1), (2, 0), (3, 1))
    assert last_common_entry(log, 4, 3) == (4, 3)  # exact last
    assert last_common_entry(log, 2, 1) == (2, 1)  # equal-term prefix
    assert last_common_entry(log, 9, 1) == (2, 1)  # term cap beats index
    assert last_common_entry(log, 1, 2) == (2, 1)  # (3,2)? no: entry3 term2 idx3>1 -> (2,1)
    assert last_common_entry(log, 4, 9) == (4, 3)  # everything below
    assert last_common_entry((), 3, 2) == (0, 0)  # empty log
    assert last_common_entry(log, 0, 0) == (0, 0)  # nothing at-or-below


def test_pull_flow_reaches_commit():
    """End-to-end protocol sanity: directed election + pull + commit path.

    Note the spec property this path must respect: AcceptPullEntriesRequest
    requires an entry BEYOND the follower's last (PullRaft.tla:470
    `index <= Len(log[i])`), so the leader needs |Value| >= 2 entries before
    a follower's matchIndex can reach 1 and anything can commit — commit is
    unreachable in the 1-value model."""
    params = PullRaftParams(
        n_servers=3, n_values=2, max_elections=1, max_restarts=0, msg_slots=32
    )
    oracle = oracle_for(params)
    st = oracle.init_state()

    def step(label_prefix):
        nonlocal st
        for label, s2 in oracle.successors(st):
            if label.startswith(label_prefix):
                st = s2
                return
        raise AssertionError(f"no successor matching {label_prefix!r}")

    step("RequestVote(0)")
    step("UpdateTerm")  # recipient fences to term 2 first (two-step receipt)
    step("HandleRequestVoteRequest")  # the fenced server grants
    step("HandleRequestVoteResponse")
    step("BecomeLeader(0)")
    step("ClientRequest(0,0)")
    step("ClientRequest(0,1)")
    step("SendPullEntriesRequest(1,0)")
    step("AcceptPullEntriesRequest")  # entry 1 to follower 1
    step("HandleSuccessPullEntriesResponse")
    step("SendPullEntriesRequest(1,0)")  # now at lastIndex=1
    step("AcceptPullEntriesRequest")  # matchIndex[0][1]=1 -> commit idx 1
    assert st["commitIndex"][0] == 1
    assert st["acked"][0] is True


@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_reference_pull_cfgs_load_with_diagnosis():
    from raft_tpu.utils.cfg import CfgError, parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    for name in ("PullRaft", "PullRaftVariant2"):
        path = f"/root/reference/specifications/pull-raft/{name}.cfg"
        # strict parse must surface the documented cfg bug
        with pytest.raises(CfgError, match="undeclared model value 'v2'"):
            parse_cfg(path)
        cfg = parse_cfg(path, lenient=True)
        assert len(cfg.diagnostics) == 1
        setup = build_from_cfg(cfg, msg_slots=16)
        assert setup.model.name == name
        assert setup.model.p.n_servers == 3
        assert setup.model.p.n_values == 2  # after repair
        assert setup.model.p.variant2 == (name == "PullRaftVariant2")
        assert setup.invariants == ("LeaderHasAllAckedValues", "NoLogDivergence")
        assert setup.symmetry
