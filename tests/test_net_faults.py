"""Opt-in network-fault actions (Raft.tla:508-523, --net-faults).

DuplicateMessage re-delivers a record already in the bag DOMAIN;
DropMessage discards one delivery. The TLA+ duplicate is unbounded (the
disjuncts are commented out of Next at Raft.tla:540-541 for that
reason); the lowering gates it on count < max_msg_copies, a documented
divergence, so the fault-injected state space stays finite and these
tests can insist on host/device count parity.
"""

import numpy as np
import pytest

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.checker.device_bfs import DeviceBFS
from raft_tpu.models.raft import EMPTY, RaftModel, RaftParams

PARAMS = RaftParams(
    n_servers=2, n_values=1, max_elections=1, max_restarts=0,
    msg_slots=12, net_faults=True,
)
BASE = RaftParams(
    n_servers=2, n_values=1, max_elections=1, max_restarts=0, msg_slots=12,
)
INVS = ("LeaderHasAllAckedValues", "NoLogDivergence")


@pytest.fixture(scope="module")
def model():
    return RaftModel(PARAMS)


def test_action_table_grows_by_two_ranks(model):
    base = RaftModel(BASE)
    assert model.ACTION_NAMES[: len(base.ACTION_NAMES)] == base.ACTION_NAMES
    assert model.ACTION_NAMES[-2:] == ["DuplicateMessage", "DropMessage"]
    assert model._r_dup == len(base.ACTION_NAMES)
    assert model._r_drop == model._r_dup + 1
    # one binding per slot per fault, appended after HandleMessage
    assert model.A == base.A + 2 * PARAMS.msg_slots
    tail = model.bindings[-2 * PARAMS.msg_slots:]
    assert [b[0] for b in tail[: PARAMS.msg_slots]] == (
        ["DuplicateMessage"] * PARAMS.msg_slots
    )
    assert [b[0] for b in tail[PARAMS.msg_slots:]] == (
        ["DropMessage"] * PARAMS.msg_slots
    )


def _state_with_message(model):
    """Expand from Init until some successor holds a single-count
    record; return (state vector, slot index)."""
    states = model.init_states()
    for _ in range(3):
        succs, valid, _, _ = map(np.asarray, model.expand(states))
        flat = succs[valid]
        cnt = model.layout.get(flat, "msg_cnt")
        hi = model.layout.get(flat, "msg_hi")
        hits = np.argwhere((cnt == 1) & (hi != EMPTY))
        if hits.size:
            b, m = hits[0]
            return flat[b], int(m)
        states = flat
    raise AssertionError("no reachable state with a pending message")


def test_duplicate_bounded_by_max_msg_copies(model):
    s, m = _state_with_message(model)
    assert PARAMS.max_msg_copies == 2
    valid, succ, rank, ovf = model._duplicate_message(s, m)
    assert bool(valid) and int(rank) == model._r_dup and not bool(ovf)
    succ = np.asarray(succ)
    assert int(model.layout.get(succ, "msg_cnt")[m]) == 2
    # only the count moved — the record payload is untouched
    assert np.array_equal(
        model.layout.get(succ, "msg_hi"), model.layout.get(s, "msg_hi")
    )
    # a second duplicate of the same record exceeds the copy bound
    valid2, _, _, _ = model._duplicate_message(succ, m)
    assert not bool(valid2)


def test_drop_discards_one_delivery(model):
    s, m = _state_with_message(model)
    dup = np.asarray(model._duplicate_message(s, m)[1])
    valid, succ, rank, _ = model._drop_message(dup, m)
    assert bool(valid) and int(rank) == model._r_drop
    assert int(model.layout.get(np.asarray(succ), "msg_cnt")[m]) == 1
    # dropping the single original empties the delivery count
    valid1, succ1, _, _ = model._drop_message(s, m)
    assert bool(valid1)
    assert int(model.layout.get(np.asarray(succ1), "msg_cnt")[m]) == 0


def test_faults_invalid_on_empty_slot(model):
    s = model.init_states()[0]  # Init has an empty bag
    for m in range(PARAMS.msg_slots):
        assert not bool(model._duplicate_message(s, m)[0])
        assert not bool(model._drop_message(s, m)[0])


def test_net_faults_fire_and_cover(model):
    """Tier-1 smoke: a shallow fault-injected run reports the two new
    coverage rows and both fault actions actually fire."""
    res = BFSChecker(model, invariants=INVS, symmetry=True, chunk=256).run(
        max_depth=3
    )
    assert res.violation is None
    assert len(res.coverage) == len(model.ACTION_NAMES)
    assert res.coverage[model._r_dup][1] > 0, "DuplicateMessage never fired"
    assert res.coverage[model._r_drop][1] > 0, "DropMessage never fired"


@pytest.mark.slow
def test_net_faults_host_device_parity_and_coverage(model):
    """Fault-injected spaces are where Duplicate interleavings bite:
    the two engines must agree state for state, and the coverage table
    must show both fault actions actually firing."""
    depth = 4
    host = BFSChecker(model, invariants=INVS, symmetry=True, chunk=256).run(
        max_depth=depth
    )
    dev = DeviceBFS(
        model, invariants=INVS, symmetry=True, chunk=256,
        frontier_cap=1 << 13, seen_cap=1 << 16, journal_cap=1 << 16,
    ).run(max_depth=depth)
    assert host.violation is None and dev.violation is None
    assert dev.distinct == host.distinct
    assert dev.total == host.total
    assert dev.depth_counts == host.depth_counts
    assert dev.terminal == host.terminal
    for cov in (host.coverage, dev.coverage):
        assert len(cov) == len(model.ACTION_NAMES)
        assert cov[model._r_dup][1] > 0, "DuplicateMessage never fired"
        assert cov[model._r_drop][1] > 0, "DropMessage never fired"
    assert host.coverage == dev.coverage
    # faults strictly enlarge the space vs the same constants without
    base = BFSChecker(
        RaftModel(BASE), invariants=INVS, symmetry=True, chunk=256
    ).run(max_depth=depth)
    assert host.distinct > base.distinct


def test_net_faults_registry_gate():
    from raft_tpu.models.registry import CfgError, build_from_cfg
    from raft_tpu.utils.cfg import Cfg, ModelValue

    consts = {
        "Server": (ModelValue("s1"), ModelValue("s2")),
        "Value": (ModelValue("v1"),),
        "MaxElections": 1,
        "MaxRestarts": 0,
    }
    cfg = Cfg(path="t.cfg", constants=consts, symmetry=None,
              invariants=["NoLogDivergence"], model_values=["s1", "s2", "v1"])
    setup = build_from_cfg(cfg, spec="Raft", msg_slots=12, net_faults=True)
    assert setup.model.p.net_faults
    assert setup.model.ACTION_NAMES[-2:] == ["DuplicateMessage", "DropMessage"]
    with pytest.raises(CfgError, match="only lowered for the Raft family"):
        build_from_cfg(cfg, spec="PullRaft", msg_slots=12, net_faults=True)
