"""Guard-first sparse expansion (models/base.py SparseExpandMixin).

The contract under test, for every model family:

  1. the guard pass (``guards1``) is bit-identical to the dense
     ``_expand1`` on valid/rank/ovf — it is DCE-derived, so any drift
     means the derivation broke;
  2. the guard jaxpr materializes NO batched successor blocks (no
     [*, W]-shaped equation outputs) — the whole point of the split;
  3. ``sparse_apply`` reconstructs the compacted [VC, W] successor
     block bit-identically to the dense gather for in-budget lanes,
     with exact budget-threshold semantics (exactly-full fits, one-
     past-full sets the overflow flag and zero-fills the spilled
     lanes);
  4. all three engines produce identical runs (distinct/total/depth
     counts/coverage triples, and counterexample traces) with the
     sparse path as with the dense path, pinned via a shim that hides
     the mixin methods.

Params mirror tests/test_device_smoke.py so cached_model reuses the
already-built lowerings.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.checker.device_bfs import DeviceBFS
from raft_tpu.parallel.sharded import ShardedBFS


def _raft():
    from raft_tpu.models.raft import RaftParams, cached_model

    return cached_model(RaftParams(
        n_servers=2, n_values=2, max_elections=2, max_restarts=0,
        msg_slots=16,
    ))


def _pull_raft():
    from raft_tpu.models.pull_raft import PullRaftParams, cached_model

    return cached_model(PullRaftParams(
        n_servers=3, n_values=1, max_elections=2, max_restarts=0,
        msg_slots=24,
    ))


def _kraft():
    from raft_tpu.models.kraft import KRaftParams, cached_model

    return cached_model(KRaftParams(
        n_servers=3, n_values=1, max_elections=2, max_restarts=0,
        msg_slots=24,
    ))


def _joint_raft():
    from raft_tpu.models.joint_raft import JointRaftParams, cached_model

    return cached_model(JointRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=1,
        max_restarts=0, max_reconfigs=1, max_values_per_term=1,
        reconfig_type=2, msg_slots=64,
    ))


def _reconfig_raft():
    from raft_tpu.models.reconfig_raft import (
        ReconfigRaftParams, cached_model,
    )

    return cached_model(ReconfigRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=1,
        max_restarts=0, max_values_per_term=1, max_add_reconfigs=1,
        max_remove_reconfigs=1, min_cluster_size=2, max_cluster_size=3,
        msg_slots=64,
    ))


def _kraft_reconfig():
    from raft_tpu.models.kraft_reconfig import (
        KRaftReconfigParams, cached_model,
    )

    return cached_model(KRaftReconfigParams(
        n_hosts=3, n_values=1, init_cluster_size=2, min_cluster_size=2,
        max_cluster_size=3, max_elections=1, max_restarts=1,
        max_values_per_epoch=1, max_add_reconfigs=1,
        max_remove_reconfigs=1, max_spawned_servers=4, msg_slots=24,
    ))


FAMILIES = {
    "raft": _raft,
    "pull_raft": _pull_raft,
    "kraft": _kraft,
    "joint_raft": _joint_raft,
    "reconfig_raft": _reconfig_raft,
    "kraft_reconfig": _kraft_reconfig,
}


class DenseShim:
    """Model proxy that hides the sparse expand contract, forcing every
    engine down the legacy dense path (the parity reference)."""

    def __init__(self, inner):
        self.__dict__["_inner"] = inner

    def __getattr__(self, name):
        if name in ("sparse_apply", "host_apply"):
            raise AttributeError(name)
        return getattr(self.__dict__["_inner"], name)


def _frontier(model, depth=3, cap=512):
    """A real reachable frontier: a few dense waves from init with
    exact-bytes dedup (guard behaviour on reachable states is what the
    parity must hold on; random bit patterns may be unreachable)."""
    W = model.layout.W
    frontier = model.init_states()
    seen = set(s.tobytes() for s in np.asarray(frontier))
    for _ in range(depth):
        B = 256
        nxt = []
        for off in range(0, len(frontier), B):
            cs = frontier[off:off + B]
            nb = len(cs)
            if nb < B:
                cs = np.concatenate(
                    [cs, np.repeat(cs[-1:], B - nb, axis=0)])
            succs, valid, _, _ = jax.device_get(model.expand(cs))
            valid = np.array(valid)
            valid[nb:] = False
            flat = np.array(succs).reshape(-1, W)
            for i in np.nonzero(valid.reshape(-1))[0]:
                t = flat[i].tobytes()
                if t not in seen:
                    seen.add(t)
                    nxt.append(flat[i])
            if len(seen) > 4 * cap:
                break
        if not nxt:
            break
        frontier = np.array(nxt, dtype=np.int32)
        if len(frontier) >= cap:
            break
    return np.asarray(frontier)[:cap]


def _chunk_of(model, C=64):
    fr = _frontier(model)
    reps = -(-C // len(fr))
    return np.tile(fr, (reps, 1))[:C]


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_guards_bit_identical_to_dense(family):
    model = FAMILIES[family]()
    batch = jnp.asarray(_chunk_of(model))
    _, valid, rank, ovf = jax.device_get(
        jax.jit(lambda b: jax.vmap(model._expand1)(b))(batch))
    gv, gr, go = jax.device_get(
        jax.jit(lambda b: jax.vmap(model.guards1)(b))(batch))
    np.testing.assert_array_equal(np.asarray(valid), np.asarray(gv))
    np.testing.assert_array_equal(np.asarray(rank), np.asarray(gr))
    np.testing.assert_array_equal(np.asarray(ovf), np.asarray(go))


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_guard_jaxpr_writes_no_successor_blocks(family):
    """The guard jaxpr must not materialize any [*, W] successor block:
    that is the work the split exists to avoid. (Single [W]-vectors are
    fine — the input state itself is one.)

    The jaxpr inspection migrated to the guard-purity lint pass
    (raft_tpu.analysis.guard_purity.check_model), which generalizes it
    with the declared-lane read audit; this wrapper runs the pass on
    each family and pins a clean report."""
    from raft_tpu.analysis import guard_purity

    findings = []
    guard_purity.check_model(family, FAMILIES[family](), findings)
    assert not findings, [f.render() for f in findings]


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_sparse_apply_parity_loose_plan(family):
    model = FAMILIES[family]()
    C = 64
    A, W = model.A, model.layout.W
    VC = min(C * A, C * 16)
    batch = jnp.asarray(_chunk_of(model, C))
    succs, valid, _, _ = jax.jit(
        lambda b: jax.vmap(model._expand1)(b))(batch)
    vflat = valid.reshape(-1)
    vpos = jnp.cumsum(vflat) - 1
    sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
    sel = (
        jnp.full((VC + 1,), C * A, jnp.int32)
        .at[sdst]
        .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
    )
    selv = sel < C * A
    dense = np.asarray(jnp.concatenate(
        [succs.reshape(C * A, W), jnp.zeros((1, W), jnp.int32)], axis=0,
    )[sel])
    plan = model.sparse_plan(C, VC)  # loose: overflow-impossible
    flatc, ovf = jax.device_get(jax.jit(
        lambda b, s, sv: model.sparse_apply(b, s, sv, plan)
    )(batch, sel, selv))
    assert not bool(ovf)
    np.testing.assert_array_equal(dense, np.asarray(flatc))


def test_apply_budget_exact_thresholds():
    """Exactly-full budgets fit without overflow and stay bit-identical;
    one-past-full sets the overflow flag, zero-fills the spilled lanes
    of the squeezed group, and leaves every other lane bit-identical."""
    model = _raft()
    C = 64
    A, W = model.A, model.layout.W
    VC = C * A  # full worklist: every enabled lane compacts in
    batch = jnp.asarray(_chunk_of(model, C))
    succs, valid, _, _ = jax.jit(
        lambda b: jax.vmap(model._expand1)(b))(batch)
    vflat = valid.reshape(-1)
    vpos = jnp.cumsum(vflat) - 1
    sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
    sel = (
        jnp.full((VC + 1,), C * A, jnp.int32)
        .at[sdst]
        .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
    )
    selv = sel < C * A
    dense = np.asarray(jnp.concatenate(
        [succs.reshape(C * A, W), jnp.zeros((1, W), jnp.int32)], axis=0,
    )[sel])

    groups = model.sparse_groups()
    valid_h = np.asarray(valid)
    counts = [int(valid_h[:, g.off:g.off + g.n].sum()) for g in groups]
    gi = int(np.argmax(counts))  # squeeze the busiest group
    assert counts[gi] >= 2, "frontier too shallow to exercise budgets"

    # exactly-full: per-group budgets == enabled counts
    plan_exact = tuple(counts)
    flatc, ovf = jax.device_get(jax.jit(
        lambda b, s, sv: model.sparse_apply(b, s, sv, plan_exact)
    )(batch, sel, selv))
    assert not bool(ovf)
    np.testing.assert_array_equal(dense, np.asarray(flatc))

    # one-past-full: the squeezed group's LAST worklist lane spills
    plan_tight = tuple(
        c - 1 if i == gi else c for i, c in enumerate(counts))
    flatc_t, ovf_t = jax.device_get(jax.jit(
        lambda b, s, sv: model.sparse_apply(b, s, sv, plan_tight)
    )(batch, sel, selv))
    assert bool(ovf_t)
    flatc_t = np.asarray(flatc_t)
    g = groups[gi]
    sel_h = np.asarray(sel)
    cand = np.where(sel_h < C * A, sel_h % A, -1)
    in_group = (cand >= g.off) & (cand < g.off + g.n)
    spilled = np.zeros(VC, dtype=bool)
    spilled[np.nonzero(in_group)[0][-1]] = True  # lane past the budget
    np.testing.assert_array_equal(dense[~spilled], flatc_t[~spilled])
    assert (flatc_t[spilled] == 0).all()


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_host_engine_parity(family):
    model = FAMILIES[family]()
    inv = tuple(list(model.invariants)[:1])
    sparse = BFSChecker(model, invariants=inv, symmetry=True, chunk=256)
    dense = BFSChecker(
        DenseShim(model), invariants=inv, symmetry=True, chunk=256)
    assert sparse._sparse and not dense._sparse
    rs, rd = sparse.run(max_depth=3), dense.run(max_depth=3)
    assert (rs.distinct, rs.total, rs.depth_counts, rs.terminal) == (
        rd.distinct, rd.total, rd.depth_counts, rd.terminal)
    assert rs.coverage == rd.coverage


_HEAVY = ("joint_raft", "kraft_reconfig", "reconfig_raft")


@pytest.mark.parametrize(
    "family",
    [f for f in sorted(FAMILIES) if f not in _HEAVY]
    + [pytest.param(f, marks=pytest.mark.slow) for f in _HEAVY],
)
def test_device_engine_parity(family):
    model = FAMILIES[family]()
    inv = tuple(list(model.invariants)[:1])
    kw = dict(invariants=inv, symmetry=True, chunk=128,
              frontier_cap=1 << 12, seen_cap=1 << 15)
    sparse = DeviceBFS(model, **kw)
    dense = DeviceBFS(DenseShim(model), **kw)
    assert sparse._sparse and not dense._sparse
    rs, rd = sparse.run(max_depth=3), dense.run(max_depth=3)
    assert (rs.distinct, rs.total, rs.depth_counts, rs.terminal) == (
        rd.distinct, rd.total, rd.depth_counts, rd.terminal)
    assert rs.coverage == rd.coverage


def test_sharded_engine_parity():
    model = _raft()
    inv = tuple(list(model.invariants)[:1])
    kw = dict(invariants=inv, symmetry=True, chunk=128,
              frontier_cap=1 << 12, seen_cap=1 << 15)
    sparse = ShardedBFS(model, **kw)
    dense = ShardedBFS(DenseShim(model), **kw)
    assert sparse._sparse and not dense._sparse
    rs, rd = sparse.run(max_depth=3), dense.run(max_depth=3)
    assert (rs.distinct, rs.total, rs.depth_counts, rs.terminal) == (
        rd.distinct, rd.total, rd.depth_counts, rd.terminal)
    assert rs.coverage == rd.coverage


@pytest.mark.slow
@pytest.mark.parametrize("family", sorted(set(FAMILIES) - {"raft"}))
def test_sharded_engine_parity_all_families(family):
    model = FAMILIES[family]()
    inv = tuple(list(model.invariants)[:1])
    kw = dict(invariants=inv, symmetry=True, chunk=128,
              frontier_cap=1 << 12, seen_cap=1 << 15)
    rs = ShardedBFS(model, **kw).run(max_depth=3)
    rd = ShardedBFS(DenseShim(model), **kw).run(max_depth=3)
    assert (rs.distinct, rs.total, rs.depth_counts, rs.coverage) == (
        rd.distinct, rd.total, rd.depth_counts, rd.coverage)


def test_violation_trace_parity():
    """A violating run must produce the identical counterexample trace
    down both paths (trace reconstruction replays the dense expand, but
    the journal it replays was written by the sparse wave loop)."""
    import jax.numpy as jnp

    from raft_tpu.models.raft import RaftParams, cached_model

    model = cached_model(RaftParams(
        n_servers=3, n_values=1, max_elections=1, max_restarts=0,
        msg_slots=16,
    ))
    lay = model.layout

    def no_commit(states):  # forbids any commit -> guaranteed to trip
        return jnp.all(lay.get(states, "commitIndex") == 0, axis=1)

    model.invariants["NoCommit"] = no_commit
    try:
        rs = BFSChecker(
            model, invariants=("NoCommit",), symmetry=True, chunk=256,
        ).run()
        rd = BFSChecker(
            DenseShim(model), invariants=("NoCommit",), symmetry=True,
            chunk=256,
        ).run()
    finally:
        del model.invariants["NoCommit"]
    assert rs.violation is not None and rd.violation is not None
    assert rs.violation.depth == rd.violation.depth
    assert rs.violation.global_id == rd.violation.global_id
    assert rs.trace is not None and rd.trace is not None
    assert [a for a, _ in rs.trace] == [a for a, _ in rd.trace]
    assert rs.trace[-1][1] == rd.trace[-1][1]


def test_e2e_sparse_run_with_telemetry(tmp_path):
    """End-to-end: a real run() down the sparse path with telemetry and
    coverage attached — the metrics stream must validate against the
    declared schema and the new wave gauges must be live (density in
    (0, 1], budget overflow 0 on a surviving run)."""
    from raft_tpu.obs import Telemetry
    from raft_tpu.obs.events import validate_lines

    model = _raft()
    inv = tuple(list(model.invariants)[:1])
    dev = DeviceBFS(
        model, invariants=inv, symmetry=True, chunk=256,
        frontier_cap=1 << 12, seen_cap=1 << 15, journal_cap=1 << 15,
    )
    assert dev._sparse  # the production path under test
    path = tmp_path / "m.jsonl"
    with Telemetry(metrics_path=str(path)) as tel:
        res = dev.run(max_depth=3, telemetry=tel, collect_metrics=True)
    with open(path) as fh:
        lines = fh.readlines()
    counts, problems = validate_lines(lines)
    assert not problems, problems
    assert counts["manifest"] == 1 and counts["summary"] == 1
    assert counts["wave"] >= 3

    import json

    waves = [json.loads(ln) for ln in lines]
    waves = [e for e in waves if e["event"] == "wave"]
    for w in waves:
        assert 0.0 <= w["enabled_density"] <= 1.0
        assert w["expand_budget_ovf"] == 0  # abort fires before this
    assert any(w["enabled_density"] > 0.0 for w in waves)
    assert res.coverage is not None and res.metrics is not None
    for wm in res.metrics:
        assert "enabled_density" in wm and "expand_budget_ovf" in wm
