"""State-space cartography (per-action coverage): registry lock-step,
device accumulation invariants, cross-engine agreement, schema
round-trip, CLI table + strict dead-action gate.

The coverage block is ``actions[rank] = [enabled, fired, new_distinct]``
with ``rank`` indexing the model's ACTION_NAMES (the Next-disjunct
order). The invariants pinned here:

  * the rank-constant table each spec lowering declares and its
    ACTION_NAMES list cannot drift apart (the AST smoke test reads the
    constants straight from the module source);
  * per action: enabled <= fired (an enabled state contributes at least
    one valid lane) and new <= fired (dedup only shrinks);
  * sum(new) == distinct states beyond the inits — every distinct state
    is attributed to exactly one action;
  * host and device engines agree on enabled/fired exactly (new
    attribution may differ per action across engines when one state is
    reachable by several actions in the same wave — the SUM still
    matches);
  * --coverage prints a table naming every action; --coverage=strict
    exits 3 when an action never fired.
"""

import dataclasses
import json

import numpy as np
import pytest

from raft_tpu.models.raft import RaftParams, cached_model

# all 12 plain-raft disjuncts fire by depth 10 at these params (restarts
# exercise Restart/UpdateTerm, a second election forces AE rejections)
COV_PARAMS = RaftParams(
    n_servers=2, n_values=1, max_elections=2, max_restarts=1, msg_slots=16
)
INVS = ("NoLogDivergence",)

MODEL_MODULES = (
    "raft", "kraft", "pull_raft", "kraft_reconfig", "joint_raft",
    "reconfig_raft",
)


def _device(model, **kw):
    from raft_tpu.checker.device_bfs import DeviceBFS

    kw.setdefault("chunk", 512)
    kw.setdefault("frontier_cap", 1 << 12)
    kw.setdefault("seen_cap", 1 << 15)
    kw.setdefault("journal_cap", 1 << 15)
    return DeviceBFS(model, invariants=INVS, symmetry=True, **kw)


# ------------------------------------------------- rank/name registry


def test_every_lowering_names_every_rank():
    """len(ACTION_NAMES) == max declared rank + 1, for every spec
    lowering — a new disjunct without a name (or a stale name list)
    breaks coverage attribution silently otherwise.

    The AST rank-table reader lives in the lane-discipline lint pass
    now (raft_tpu.analysis.lanes.module_max_rank, the migrated
    ``_module_max_rank``); this wrapper pins each module's table
    against its ACTION_NAMES the way the original did."""
    import importlib

    from raft_tpu.analysis.lanes import module_max_rank

    for name in MODEL_MODULES:
        mod = importlib.import_module(f"raft_tpu.models.{name}")
        with open(mod.__file__) as fh:
            max_rank = module_max_rank(fh.read())
        assert max_rank is not None, f"{name}: no rank table found"
        assert len(mod.ACTION_NAMES) == max_rank + 1, (
            f"{name}: {len(mod.ACTION_NAMES)} names for ranks "
            f"0..{max_rank}"
        )


def test_lane_discipline_pass_clean():
    """The full lane-discipline pass (ACTION_NAMES lock-step across the
    registry PLUS ``_cv`` routing of fleet-dynamic constants) reports
    nothing on the shipped tree — the superset contract of the wrapper
    above, run exactly as ``raft_tpu lint --pass lane-discipline``."""
    from raft_tpu.analysis import lanes

    res = lanes.run()
    assert res.checked >= len(MODEL_MODULES)
    assert not res.findings, [f.render() for f in res.findings]


def test_raft_instance_trims_fsync_ranks():
    from raft_tpu.models import raft as raft_mod

    plain = cached_model(COV_PARAMS)
    assert plain.ACTION_NAMES == list(raft_mod.ACTION_NAMES[:12])
    fsync = cached_model(dataclasses.replace(COV_PARAMS, has_fsync=True))
    assert fsync.ACTION_NAMES == list(raft_mod.ACTION_NAMES)
    # the shared mixin resolves labels through the instance table
    assert plain.action_label(raft_mod.R_RESTART, 0).startswith("Restart")


# ------------------------------------------------- device accumulation


def test_device_coverage_accumulation_invariants():
    from raft_tpu.obs import Telemetry

    model = cached_model(COV_PARAMS)
    with Telemetry() as tel:
        res = _device(model).run(max_depth=10, telemetry=tel)
    K = len(model.ACTION_NAMES)
    cov = np.asarray(res.coverage)
    assert cov.shape == (K, 3)
    enabled, fired, new = cov[:, 0], cov[:, 1], cov[:, 2]
    assert (enabled <= fired).all()
    assert (new <= fired).all()
    assert int(new.sum()) == res.distinct - res.depth_counts[0]
    # acceptance: on this config every plain-raft action fires
    assert (fired > 0).all(), (
        f"dead actions: "
        f"{[model.ACTION_NAMES[r] for r in np.nonzero(fired == 0)[0]]}"
    )
    covs = tel.coverage_events()
    assert covs[-1]["final"] is True
    assert covs[-1]["actions"] == res.coverage
    assert covs[-1]["actions_fired"] == K
    assert covs[-1]["frontier_hist"] == res.depth_counts
    # memo fill is only read at the final snapshot (mid-run it would
    # cost a device sync)
    assert all(e["canon_memo_fill"] is None for e in covs[:-1])
    assert covs[-1]["canon_memo_fill"] is not None


def test_host_and_device_engines_agree():
    from raft_tpu.checker.bfs import BFSChecker

    model = cached_model(COV_PARAMS)
    host = BFSChecker(model, invariants=INVS, symmetry=True, chunk=512).run(
        max_depth=6
    )
    dev = _device(model).run(max_depth=6)
    h, d = np.asarray(host.coverage), np.asarray(dev.coverage)
    assert h[:, :2].tolist() == d[:, :2].tolist()  # enabled/fired exact
    assert int(h[:, 2].sum()) == int(d[:, 2].sum())
    assert int(d[:, 2].sum()) == dev.distinct - dev.depth_counts[0]


# ------------------------------------------------- schema round-trip


def _cov_event(wave, actions, final=False):
    return {
        "event": "coverage", "wave": wave, "depth": wave,
        "actions": actions, "actions_total": len(actions),
        "actions_fired": sum(1 for r in actions if r[1]),
        "seen_lanes": [8], "seen_real": 4, "probe_runs": 1,
        "frontier_hist": [1] * (wave + 1), "canon_memo_fill": None,
        "final": final,
    }


def _stream(events):
    return [json.dumps(e) for e in events]


def test_coverage_schema_roundtrip_and_monotonicity():
    from raft_tpu.obs import MANIFEST_KEYS, SUMMARY_KEYS, WAVE_KEYS
    from raft_tpu.obs.events import validate_lines

    def fields(keys, **kw):
        ev = dict.fromkeys(keys, 0)
        ev.update(kw)
        return ev

    man = fields(MANIFEST_KEYS, event="manifest", action_names=["A", "B"])
    w1 = fields(WAVE_KEYS, event="wave", wave=1)
    w2 = fields(WAVE_KEYS, event="wave", wave=2)
    summ = fields(SUMMARY_KEYS, event="summary", exit_cause="exhausted")

    good = _stream([
        man, w1, _cov_event(1, [[1, 1, 1], [0, 0, 0]]),
        w2, _cov_event(2, [[2, 3, 1], [1, 1, 1]], final=True), summ,
    ])
    counts, problems = validate_lines(good)
    assert not problems, problems
    assert counts["coverage"] == 2

    # cumulative counters must never decrease cell-by-cell
    bad = _stream([
        man, w1, _cov_event(1, [[2, 2, 1], [0, 0, 0]]),
        w2, _cov_event(2, [[1, 3, 1], [1, 1, 1]], final=True), summ,
    ])
    _, problems = validate_lines(bad)
    assert any("not monotone" in p for p in problems), problems

    # coverage after the run's summary is a stream bug
    bad2 = _stream([man, w1, summ, _cov_event(1, [[1, 1, 1], [0, 0, 0]])])
    _, problems = validate_lines(bad2)
    assert any("after the run's summary" in p for p in problems), problems

    # malformed actions block (negative count / wrong arity)
    bad3 = _stream([man, w1, _cov_event(1, [[1, -1, 1], [0, 0]]), summ])
    _, problems = validate_lines(bad3)
    assert any("non-negative int triples" in p for p in problems), problems


# ----------------------------------------------------------------- CLI


CFG_TEMPLATE = """\
CONSTANTS
    n1 = n1
    n2 = n2
    v1 = v1
    Server = {{ n1, n2 }}
    Value = {{ v1 }}
    Follower = Follower
    Candidate = Candidate
    Leader = Leader
    Nil = Nil
    RequestVoteRequest = RequestVoteRequest
    RequestVoteResponse = RequestVoteResponse
    AppendEntriesRequest = AppendEntriesRequest
    AppendEntriesResponse = AppendEntriesResponse
    EqualTerm = EqualTerm
    LessOrEqualTerm = LessOrEqualTerm
    MaxElections = {elections}
    MaxRestarts = {restarts}

INIT Init
NEXT Next

INVARIANT
NoLogDivergence
"""

CLI_BASE = [
    "--platform", "cpu", "--msg-slots", "16", "--chunk", "256",
    "--frontier-cap", "4096", "--seen-cap", "16384",
    "--journal-cap", "16384",
]


@pytest.mark.slow
def test_cli_coverage_table_names_every_action(tmp_path, capsys):
    from raft_tpu.__main__ import main
    from raft_tpu.models import raft as raft_mod

    cfg = tmp_path / "Raft.cfg"
    cfg.write_text(CFG_TEMPLATE.format(elections=2, restarts=1))
    rc = main([str(cfg), *CLI_BASE, "--max-depth", "10", "--coverage"])
    cap = capsys.readouterr()
    assert rc == 0, cap.err
    assert "Action coverage" in cap.out
    for name in raft_mod.ACTION_NAMES[:12]:
        assert name in cap.out, f"table missing action {name}"
    assert "never fired" not in cap.out


@pytest.mark.slow
def test_cli_coverage_strict_gates_on_dead_action(tmp_path, capsys):
    from raft_tpu.__main__ import main

    # MaxRestarts=0 makes the Restart disjunct unreachable
    cfg = tmp_path / "Raft.cfg"
    cfg.write_text(CFG_TEMPLATE.format(elections=1, restarts=0))
    rc = main([str(cfg), *CLI_BASE, "--max-depth", "4",
               "--coverage=strict"])
    cap = capsys.readouterr()
    assert "WARNING: action Restart never fired" in cap.out
    assert rc == 3, cap.err
