"""Simulation-mode checker tests: random-walk behaviors, restart
semantics, violation detection with trace replay, CLI integration."""

import numpy as np
import pytest

from pathlib import Path

import jax
import jax.numpy as jnp

from raft_tpu.checker.simulate import Simulator
from raft_tpu.models.raft import LEADER, RaftParams, cached_model


def _model():
    return cached_model(
        RaftParams(n_servers=3, n_values=1, max_elections=2, max_restarts=0,
                   msg_slots=32)
    )


def test_simulation_runs_clean_behaviors():
    model = _model()
    sim = Simulator(
        model,
        invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
        walks=16,
        max_behavior_depth=12,
        seed=7,
    )
    res = sim.run(max_behaviors=32)
    assert res.violation is None
    assert res.behaviors >= 32
    assert res.steps > 100


@pytest.mark.slow
def test_simulation_finds_planted_violation_and_replays():
    """Plant a predicate that fails once any server is elected; random
    walks must find it quickly and the journal must replay to a labeled
    trace ending in the violating state."""
    model = _model()
    lay = model.layout

    def no_leader(states):
        st = lay.get(states, "state")
        return ~jnp.any(st == LEADER, axis=1)

    model.invariants["NoLeaderEver"] = jax.jit(no_leader)
    try:
        sim = Simulator(
            model, invariants=("NoLeaderEver",), walks=16,
            max_behavior_depth=20, seed=3,
        )
        res = sim.run(max_steps=20_000)
        assert res.violation is not None
        assert res.violation.invariant == "NoLeaderEver"
        assert res.trace is not None
        assert res.trace[0][0] == "Initial predicate"
        final = res.trace[-1][1]
        assert LEADER in final["state"]
        # the violating behavior's length matches the recorded depth
        assert len(res.trace) - 1 == res.violation.depth
        # last action is the leader election
        assert res.trace[-1][0].startswith("BecomeLeader")
    finally:
        del model.invariants["NoLeaderEver"]


@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_simulate_cli_on_flexible_raft_cfg():
    """FlexibleRaft.cfg:5 prescribes simulation mode; drive it through
    the CLI entry point (in-process)."""
    from raft_tpu.__main__ import main

    rc = main(
        [
            "/root/reference/specifications/flexible-raft/FlexibleRaft.cfg",
            "--platform", "cpu",
            "--simulate", "24",
            "--sim-depth", "10",
            "--sim-walks", "8",
            "--msg-slots", "32",
        ]
    )
    assert rc == 0
