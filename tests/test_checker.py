"""End-to-end checker tests: BFS parity with the oracle, cfg loading, traces."""

import numpy as np
import pytest

from pathlib import Path

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.models.raft import RaftModel, RaftParams, cached_model
from raft_tpu.oracle.raft_oracle import RaftOracle

REF_CFG = "/root/reference/specifications/standard-raft/Raft.cfg"


def _bfs_pair(params, invariants, symmetry=True, max_depth=None, chunk=256):
    model = cached_model(params)
    oracle = RaftOracle(
        params.n_servers, params.n_values, params.max_elections, params.max_restarts
    )
    checker = BFSChecker(model, invariants=invariants, symmetry=symmetry, chunk=chunk)
    res = checker.run(max_depth=max_depth)
    ores = oracle.bfs(invariants=invariants, symmetry=symmetry, max_depth=max_depth)
    return res, ores, checker


@pytest.mark.slow
@pytest.mark.parametrize("symmetry", [True, False])
def test_bfs_counts_match_oracle_small(symmetry):
    params = RaftParams(n_servers=3, n_values=1, max_elections=1, max_restarts=0, msg_slots=16)
    res, ores, _ = _bfs_pair(
        params, ("LeaderHasAllAckedValues", "NoLogDivergence"), symmetry=symmetry
    )
    assert res.violation is None and ores["violation"] is None
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]
    assert res.total == ores["total"]


def test_bfs_counts_match_oracle_with_restarts():
    params = RaftParams(n_servers=2, n_values=2, max_elections=2, max_restarts=1, msg_slots=24)
    res, ores, _ = _bfs_pair(
        params,
        ("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry=True,
        max_depth=8,
        chunk=512,
    )
    assert res.violation is None and ores["violation"] is None
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]
    assert res.total == ores["total"]


@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_cfg_parse_reference_raft():
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    cfg = parse_cfg(REF_CFG)
    assert cfg.init == "Init" and cfg.next == "Next"
    assert cfg.view == "view" and cfg.symmetry == "symmServers"
    assert cfg.invariants == ["LeaderHasAllAckedValues", "NoLogDivergence"]
    setup = build_from_cfg(cfg, msg_slots=16)
    assert setup.model.p.n_servers == 3
    assert setup.model.p.n_values == 1
    assert setup.model.p.max_elections == 2
    assert setup.model.p.max_restarts == 0
    assert setup.server_names == ["n1", "n2", "n3"]


def test_cfg_diagnoses_undeclared_model_value():
    from raft_tpu.utils.cfg import CfgError, parse_cfg

    text = "CONSTANTS\n    v1 = v1\n    Value = { v1, v2 }\n"
    with pytest.raises(CfgError, match="undeclared model value 'v2'"):
        parse_cfg("inline.cfg", text=text)


def test_violation_trace_on_injected_invariant():
    # A predicate that forbids any committed entry -> must be violated, and
    # the reconstructed trace must be a valid action chain from Init.
    import jax.numpy as jnp

    params = RaftParams(n_servers=3, n_values=1, max_elections=1, max_restarts=0, msg_slots=16)
    model = cached_model(params)
    lay = model.layout

    def no_commit(states):
        ci = lay.get(states, "commitIndex")
        return jnp.all(ci == 0, axis=1)

    model.invariants["NoCommit"] = no_commit
    try:
        checker = BFSChecker(model, invariants=("NoCommit",), symmetry=True, chunk=256)
        res = checker.run()
    finally:
        del model.invariants["NoCommit"]
    assert res.violation is not None
    assert res.trace is not None
    assert res.violation.depth == len(res.trace) - 1
    # the violating final state indeed commits something
    final = res.trace[-1][1]
    assert any(ci > 0 for ci in final["commitIndex"])
    # and the trace starts at Init
    oracle = RaftOracle(3, 1, 1, 0)
    assert res.trace[0][1] == oracle.init_state()
    # shortest counterexample: BFS depth of first commit
    ores = RaftOracle(3, 1, 1, 0).bfs(invariants=(), symmetry=True)
    assert res.violation.depth <= len(ores["depth_counts"])
