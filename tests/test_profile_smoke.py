"""CI smoke for the stage profiler (checker/profile.py).

One tiny fused chunk on CPU with stage timers enabled: every stage the
profiler DECLARES must actually report, so stage accounting cannot
silently rot when the chunk pipeline changes shape (the round-5 wave
fusion broke the profiler exactly that way — it kept addressing the
retired per-chunk LSM). Timings themselves are not asserted: CPU CI
noise makes any threshold flaky; presence and well-formedness are the
contract.
"""

from raft_tpu.checker.profile import DECLARED_STAGES, profile_stages, render
from raft_tpu.models.raft import RaftModel, RaftParams


def test_profile_reports_every_declared_stage():
    p = RaftParams(3, 3, max_elections=2, max_restarts=0, msg_slots=24)
    model = RaftModel(p)
    inv = tuple(list(model.invariants)[:1])
    prof = profile_stages(
        model, invariants=inv, chunk=128, frontier_cap=1 << 12,
        seen_cap=1 << 14, warm_depth=4, reps=1,
    )

    missing = [k for k in DECLARED_STAGES if k not in prof["stages_s"]]
    assert not missing, f"profiler dropped declared stages: {missing}"
    for k in DECLARED_STAGES:
        v = prof["stages_s"][k]
        assert isinstance(v, float) and v >= 0.0, (k, v)

    # the memoized canon stages must really time (not report the 0.0
    # not-applicable placeholder) on a standard symmetric model
    assert prof["stages_s"]["canon"] > 0.0
    assert prof["stages_s"]["canon_memo_hit"] > 0.0
    # both emit rows must really time: emit_append is the production
    # path, scatter the retired diagnostic kept for old-vs-new profiles
    assert prof["stages_s"]["emit_append"] > 0.0
    assert prof["stages_s"]["scatter"] > 0.0
    # raft3 (S=3) has no pruned tier path, so the tier-3 stage reports
    # its placeholder — present, exactly 0.0
    assert prof["stages_s"]["canon_tier3_local"] == 0.0
    # RaftModel carries the sparse expand contract: guards and apply
    # must really time, and the dense expand row must join the
    # diagnostic set (still measured for old-vs-new comparison, but
    # excluded from the production stage sum)
    assert prof["stages_s"]["guards"] > 0.0
    assert prof["stages_s"]["apply"] > 0.0
    assert prof["stages_s"]["expand"] > 0.0
    assert "expand" in prof["diag_rows"]

    pw = prof["per_wave_s"]
    assert 0.0 <= pw["canon_share_of_stage_sum"] <= 1.0
    assert 0.0 <= pw["expand_share_of_stage_sum"] <= 1.0
    assert pw["stage_sum_per_chunk"] > 0.0

    txt = render(prof)
    for k in DECLARED_STAGES:
        assert k in txt, f"render() dropped stage {k}"
