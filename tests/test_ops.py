"""Unit tests for bit packing, bag kernels, and hashing."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.ops.bag import bag_discard_at, bag_put, bag_sort
from raft_tpu.ops.hashing import hash_lanes
from raft_tpu.ops.packing import EMPTY, BitPacker, bits_for


def test_bits_for():
    assert bits_for(0) == 1
    assert bits_for(1) == 1
    assert bits_for(3) == 2
    assert bits_for(4) == 3


def test_packer_roundtrip():
    pk = BitPacker([("a", 3), ("b", 4), ("c", 20), ("d", 10), ("e", 1)])
    hi, lo = pk.pack(a=5, b=9, c=(1 << 20) - 1, d=1023, e=1)
    assert pk.unpack(hi, lo, "a") == 5
    assert pk.unpack(hi, lo, "b") == 9
    assert pk.unpack(hi, lo, "c") == (1 << 20) - 1
    assert pk.unpack(hi, lo, "d") == 1023
    assert pk.unpack(hi, lo, "e") == 1
    assert 0 <= hi < (1 << 30) and 0 <= lo < (1 << 30)


def test_packer_replace():
    pk = BitPacker([("a", 3), ("b", 4), ("c", 20), ("d", 10)])
    hi, lo = pk.pack(a=2, b=3, c=12345, d=77)
    hi2, lo2 = pk.replace(hi, lo, "a", 7)
    hi2, lo2 = pk.replace(hi2, lo2, "d", 3)
    assert pk.unpack(hi2, lo2, "a") == 7
    assert pk.unpack(hi2, lo2, "b") == 3
    assert pk.unpack(hi2, lo2, "c") == 12345
    assert pk.unpack(hi2, lo2, "d") == 3


def test_packer_range_check():
    pk = BitPacker([("a", 3)])
    with pytest.raises(ValueError):
        pk.pack(a=8)


def _empty_bag(m=6):
    hi = jnp.full((m,), int(EMPTY), jnp.int32)
    lo = jnp.full((m,), int(EMPTY), jnp.int32)
    cnt = jnp.zeros((m,), jnp.int32)
    return hi, lo, cnt


def test_bag_put_and_discard():
    hi, lo, cnt = _empty_bag()
    hi, lo, cnt, existed, ovf = bag_put(hi, lo, cnt, jnp.int32(5), jnp.int32(7))
    assert not bool(existed) and not bool(ovf)
    hi, lo, cnt, existed, _ = bag_put(hi, lo, cnt, jnp.int32(5), jnp.int32(7))
    assert bool(existed)
    assert int(cnt[0]) == 2 and int(hi[0]) == 5
    # discard twice: count 0 but key stays in the domain (TLA+ bag semantics)
    cnt = bag_discard_at(cnt, 0)
    cnt = bag_discard_at(cnt, 0)
    assert int(cnt[0]) == 0 and int(hi[0]) == 5
    hi, lo, cnt, existed, _ = bag_put(hi, lo, cnt, jnp.int32(5), jnp.int32(7))
    assert bool(existed) and int(cnt[0]) == 1


def test_bag_sorted_canonical():
    hi, lo, cnt = _empty_bag()
    for k in [(9, 1), (2, 8), (2, 3), (5, 5)]:
        hi, lo, cnt, _, _ = bag_put(hi, lo, cnt, jnp.int32(k[0]), jnp.int32(k[1]))
    keys = list(zip(np.asarray(hi).tolist(), np.asarray(lo).tolist()))
    assert keys[:4] == [(2, 3), (2, 8), (5, 5), (9, 1)]
    assert all(h == int(EMPTY) for h, _ in keys[4:])


def test_bag_overflow_flag():
    hi, lo, cnt = _empty_bag(2)
    hi, lo, cnt, _, o1 = bag_put(hi, lo, cnt, jnp.int32(1), jnp.int32(1))
    hi, lo, cnt, _, o2 = bag_put(hi, lo, cnt, jnp.int32(2), jnp.int32(2))
    hi, lo, cnt, _, o3 = bag_put(hi, lo, cnt, jnp.int32(3), jnp.int32(3))
    assert not bool(o1) and not bool(o2) and bool(o3)


def test_hash_lanes_sensitivity():
    v = jnp.zeros((4, 8), jnp.int32)
    h0 = np.asarray(hash_lanes(v))
    assert len(set(h0.tolist())) == 1
    v2 = v.at[0, 3].set(1)
    v3 = v.at[0, 4].set(1)
    h2 = np.asarray(hash_lanes(v2))
    h3 = np.asarray(hash_lanes(v3))
    assert h2[0] != h0[0] and h3[0] != h0[0] and h2[0] != h3[0]
    assert h2[1] == h0[1]

