"""DeviceBFS (the device-resident fast path) parity + trace tests.

These are the round-2 hand-run checks promoted to tests (oracle parity,
trace validity, chunk-size sweep), per the round-2 verdict. The chunk sweep
is the CPU half of the defense against the axon scatter miscompile fixed in
ops/bag.py (one-hot writes); the TPU half is the runtime parity gate in
checker/parity.py that bench.py runs on the real chip.
"""

import numpy as np
import pytest

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.checker.device_bfs import DeviceBFS
from raft_tpu.models.raft import RaftModel, RaftParams, cached_model

SMALL = RaftParams(n_servers=3, n_values=1, max_elections=1, max_restarts=0, msg_slots=16)
INVS = ("LeaderHasAllAckedValues", "NoLogDivergence")


def _device(params, invariants, symmetry=True, chunk=512, **kw):
    kw.setdefault("frontier_cap", 1 << 14)
    kw.setdefault("seen_cap", 1 << 17)
    kw.setdefault("journal_cap", 1 << 17)
    return DeviceBFS(
        cached_model(params), invariants=invariants, symmetry=symmetry, chunk=chunk, **kw
    )


@pytest.mark.slow
@pytest.mark.parametrize("symmetry", [True, False])
def test_device_bfs_matches_host_checker(symmetry):
    model = cached_model(SMALL)
    host = BFSChecker(model, invariants=INVS, symmetry=symmetry, chunk=256)
    hres = host.run()
    dres = _device(SMALL, INVS, symmetry=symmetry).run()
    assert dres.violation is None and hres.violation is None
    assert dres.distinct == hres.distinct
    assert dres.depth_counts == hres.depth_counts
    assert dres.total == hres.total
    assert dres.terminal == hres.terminal
    assert dres.exhausted


@pytest.mark.slow
def test_device_bfs_chunk_sweep():
    """Identical counts at several chunk sizes — the invariance that the
    round-2 TPU dedup miscount silently broke."""
    base = None
    for chunk in (256, 512, 1024):
        res = _device(SMALL, INVS, chunk=chunk).run()
        sig = (res.distinct, res.total, res.depth_counts, res.terminal)
        if base is None:
            base = sig
        else:
            assert sig == base, f"chunk={chunk} diverged: {sig} != {base}"


def test_device_bfs_trace_on_injected_invariant():
    import jax.numpy as jnp

    model = cached_model(SMALL)
    lay = model.layout

    def no_commit(states):
        ci = lay.get(states, "commitIndex")
        return jnp.all(ci == 0, axis=1)

    model.invariants["NoCommit"] = no_commit
    try:
        res = _device(SMALL, ("NoCommit",)).run()
    finally:
        del model.invariants["NoCommit"]
    assert res.violation is not None
    assert res.trace is not None
    assert res.violation.depth == len(res.trace) - 1
    final = res.trace[-1][1]
    assert any(ci > 0 for ci in final["commitIndex"])
    # shortest-counterexample depth must agree with the host checker's
    model.invariants["NoCommit"] = no_commit
    try:
        hres = BFSChecker(model, invariants=("NoCommit",), symmetry=True, chunk=256).run()
    finally:
        del model.invariants["NoCommit"]
    assert res.violation.depth == hres.violation.depth


@pytest.mark.slow
def test_device_bfs_max_depth_and_time_budget():
    res = _device(SMALL, INVS).run(max_depth=5)
    assert not res.exhausted
    assert res.depth == 5
    full = _device(SMALL, INVS).run()
    assert full.exhausted
    assert full.depth_counts[:6] == res.depth_counts[:6]


def test_device_bfs_rejects_indivisible_chunk():
    with pytest.raises(AssertionError):
        _device(SMALL, INVS, chunk=768, frontier_cap=1 << 13)


@pytest.mark.slow
def test_device_bfs_capacity_growth():
    """Tiny initial caps; the run must grow all three buffers between
    waves and still produce exact counts (no states dropped)."""
    ref = _device(SMALL, INVS).run()
    grown = _device(
        SMALL,
        INVS,
        chunk=128,
        frontier_cap=256,
        seen_cap=512,
        journal_cap=512,
        max_frontier_cap=1 << 14,
        max_seen_cap=1 << 17,
        max_journal_cap=1 << 17,
    )
    res = grown.run()
    assert grown.FCAP > 256 and grown.JCAP > 512
    # the LSM seen-set grows by occupying levels, not by resizing SCAP
    assert grown._lsm.lanes() > 512
    assert res.distinct == ref.distinct
    assert res.depth_counts == ref.depth_counts
    assert res.total == ref.total
    assert res.terminal == ref.terminal


@pytest.mark.slow
def test_device_bfs_checkpoint_resume(tmp_path):
    """Split a run at a depth cap via checkpoint, resume in a fresh
    checker, and require the stitched result to equal a straight run —
    including a violation trace that crosses the checkpoint boundary."""
    import jax.numpy as jnp

    model = cached_model(SMALL)
    lay = model.layout

    def no_commit(states):
        ci = lay.get(states, "commitIndex")
        return jnp.all(ci == 0, axis=1)

    ck = str(tmp_path / "run.ckpt.npz")
    model.invariants["NoCommit"] = no_commit
    try:
        first = _device(SMALL, ("NoCommit",))
        r1 = first.run(max_depth=4, checkpoint_path=ck, checkpoint_every_s=0.0)
        assert r1.violation is None and not r1.exhausted
        second = _device(SMALL, ("NoCommit",))
        r2 = second.run(resume=ck)
        straight = _device(SMALL, ("NoCommit",)).run()
    finally:
        del model.invariants["NoCommit"]
    assert r2.violation is not None and straight.violation is not None
    assert r2.violation.depth == straight.violation.depth
    assert r2.distinct == straight.distinct
    assert r2.depth_counts == straight.depth_counts
    assert [a for a, _ in r2.trace] == [a for a, _ in straight.trace]


@pytest.mark.slow
def test_device_bfs_final_checkpoint_on_capped_exit(tmp_path):
    """A depth/budget-capped run with checkpoint_path must leave a
    resumable file even when the periodic timer never fired (default
    300 s cadence on a short run used to produce NO checkpoint at all)."""
    import os

    ck = str(tmp_path / "final.ckpt.npz")
    r1 = _device(SMALL, INVS).run(max_depth=3, checkpoint_path=ck)
    assert not r1.exhausted
    assert os.path.exists(ck)
    r2 = _device(SMALL, INVS).run(resume=ck)
    straight = _device(SMALL, INVS).run()
    assert r2.distinct == straight.distinct
    assert r2.depth_counts == straight.depth_counts


def test_device_bfs_checkpoint_invariant_mismatch(tmp_path):
    """Resuming with a different invariant set must be refused: states
    explored before the checkpoint were never evaluated against the new
    invariants, so the resumed run's verdict would be unsound."""
    ck = str(tmp_path / "inv.ckpt.npz")
    _device(SMALL, INVS).run(max_depth=3, checkpoint_path=ck)
    with pytest.raises(ValueError, match="checkpoint is for spec"):
        _device(SMALL, ("NoLogDivergence",)).run(resume=ck)


def test_device_bfs_checkpoint_spec_mismatch(tmp_path):
    other = RaftParams(
        n_servers=2, n_values=1, max_elections=1, max_restarts=0, msg_slots=16
    )
    ck = str(tmp_path / "run.ckpt.npz")
    _device(SMALL, INVS).run(max_depth=3, checkpoint_path=ck, checkpoint_every_s=0.0)
    with pytest.raises(ValueError, match="checkpoint is for spec"):
        _device(other, INVS).run(resume=ck)
