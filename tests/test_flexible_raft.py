"""FlexibleRaft differential tests: variant kernels vs the variant oracle,
plus full-BFS count parity and reference-cfg loading."""

import numpy as np
import pytest

from pathlib import Path

import jax

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.models.raft import RaftModel, RaftParams, cached_model
from raft_tpu.oracle.raft_oracle import oracle_for

from conftest import collect_states as _collect_states

FLEX = RaftParams(
    n_servers=3,
    n_values=1,
    max_elections=2,
    max_restarts=0,
    msg_slots=24,
    election_quorum=2,
    replication_quorum=3,
    strict_send_once=True,
    has_pending_response=False,
    trunc_term_mismatch=True,
)


def test_flexible_successor_sets_match_oracle():
    model = cached_model(FLEX)
    oracle = oracle_for(FLEX)
    states = _collect_states(oracle, max_depth=6, cap=150)
    vecs = np.stack([model.encode(st) for st in states])
    succs, valid, rank, ovf = jax.device_get(model.expand(vecs))
    assert not np.any(valid & ovf)
    for b, st in enumerate(states):
        got = sorted(
            oracle.serialize_full(model.decode(succs[b, a]))
            for a in range(model.A)
            if valid[b, a]
        )
        want = sorted(oracle.serialize_full(s2) for _l, s2 in oracle.successors(st))
        assert got == want, f"successor mismatch at state {b}"


@pytest.mark.slow
def test_flexible_bfs_counts_match_oracle():
    model = cached_model(FLEX)
    oracle = oracle_for(FLEX)
    checker = BFSChecker(
        model,
        invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry=True,
        chunk=256,
    )
    res = checker.run(max_depth=10)
    ores = oracle.bfs(
        invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry=True,
        max_depth=10,
    )
    assert res.violation is None and ores["violation"] is None
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]


@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_reference_flexible_cfg_loads():
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    cfg = parse_cfg("/root/reference/specifications/flexible-raft/FlexibleRaft.cfg")
    setup = build_from_cfg(cfg, msg_slots=16)
    p = setup.model.p
    assert p.n_servers == 5 and p.election_quorum == 3 and p.replication_quorum == 4
    assert p.strict_send_once and not p.has_pending_response and p.trunc_term_mismatch
    assert setup.model.name == "FlexibleRaft"
