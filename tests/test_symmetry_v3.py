"""Property tests for the canonical fingerprint (ops/symmetry.py):
sort-free multiset bag hashing + signature-pruned permutation min, plus
the v5 additions — k-round 1-WL signature refinement, the tie-group-
local tier 3, and the raw-keyed canon memo.

The correctness contract (module docstring there):
  - the per-server signature is permutation-EQUIVARIANT at every
    refinement depth,
  - the fast tiered path is bit-identical to the brute-force masked min
    over the full S! table (mode="full") at the SAME refinement depth —
    for every tier route (argsort-only, swap products, tie-group-local
    blocks, full-table drain),
  - memoization is value-preserving: a memo hit returns exactly the
    cold-canon fingerprint, under any table size (including constant
    eviction at tiny capacities),
  - fingerprints are orbit-invariant and separate orbits exactly like
    the oracle's canonical view (TLC's SYMMETRY semantics,
    ``Raft.tla:116``).
"""

import itertools

import numpy as np
import pytest

from raft_tpu.models.pull_raft import PullRaftModel, PullRaftParams
from raft_tpu.models.raft import RaftModel, RaftParams
from raft_tpu.oracle.pull_oracle import PullRaftOracle
from raft_tpu.oracle.raft_oracle import RaftOracle
from raft_tpu.ops.hashing import U64_MAX
from raft_tpu.ops.symmetry import Canonicalizer

from conftest import collect_states


def raft3():
    p = RaftParams(n_servers=3, n_values=1, max_elections=1, max_restarts=1,
                   msg_slots=24)
    return RaftModel(p), RaftOracle(p.n_servers, p.n_values, p.max_elections,
                                    p.max_restarts)


def raft5():
    p = RaftParams(n_servers=5, n_values=2, max_elections=2, max_restarts=0,
                   msg_slots=48)
    return RaftModel(p), RaftOracle(p.n_servers, p.n_values, p.max_elections,
                                    p.max_restarts)


def pull3():
    p = PullRaftParams(n_servers=3, n_values=1, max_elections=2,
                       max_restarts=0, msg_slots=24)
    return (PullRaftModel(p),
            PullRaftOracle(p.n_servers, p.n_values, p.max_elections,
                           p.max_restarts))


CASES = {"raft3": raft3, "raft5": raft5, "pull3": pull3}


def canon_pair(model, refine_rounds: int = 3):
    auto = Canonicalizer.for_model(model, symmetry=True,
                                   refine_rounds=refine_rounds)
    full = Canonicalizer(
        model.layout, model.packer,
        msg_server_fields=getattr(model, "msg_server_fields",
                                  ("msource", "mdest")),
        msg_server_nil_fields=getattr(model, "msg_server_nil_fields", ()),
        msg_perm_spec=getattr(model, "msg_perm_spec", None),
        symmetry=True, mode="full", refine_rounds=refine_rounds,
    )
    return auto, full


def states_of(name, depth=4, cap=150):
    model, oracle = CASES[name]()
    states = collect_states(oracle, max_depth=depth, cap=cap)
    vecs = np.stack([model.encode(st) for st in states])
    return model, oracle, states, vecs


@pytest.mark.parametrize("name", list(CASES))
def test_auto_equals_bruteforce(name):
    model, _oracle, _states, vecs = states_of(name)
    auto, full = canon_pair(model)
    fa = np.asarray(auto.fingerprints(vecs))
    fb = np.asarray(full.fingerprints(vecs))
    assert np.array_equal(fa, fb)
    assert not np.any(fa == U64_MAX)


@pytest.mark.parametrize("name", ["raft3", "raft5"])
def test_auto_equals_bruteforce_tie_heavy(name):
    # a batch of replicated Init states is 100% signature-tied with
    # S-sized (all-tied) groups — the full-S!-table drain — while the
    # reachable states mix in argsort-only, swap-product and tie-group-
    # local lanes; the adaptive blocked tier 3 must stay bit-identical
    # no matter how many heavy lanes a chunk carries (the retired
    # static-budget design fell off a whole-batch lax.cond cliff here)
    model, _oracle, _states, vecs = states_of(name, depth=3, cap=40)
    reps = np.repeat(model.init_states(), 200, axis=0)
    batch = np.concatenate([reps, vecs, reps], axis=0)
    auto, full = canon_pair(model)
    fa = np.asarray(auto.fingerprints(batch))
    fb = np.asarray(full.fingerprints(batch))
    assert np.array_equal(fa, fb)


@pytest.mark.parametrize("rounds", [1, 2, 3])
def test_refinement_rounds_bit_identical_to_bruteforce(rounds):
    # the k-round 1-WL refinement changes WHICH permutations are
    # admissible (and so the fingerprint VALUES of tied states), but at
    # every depth the pruned tiered path must equal the full-table
    # masked min computed at the SAME depth
    model, _oracle, _states, vecs = states_of("raft5", depth=3, cap=60)
    reps = np.repeat(model.init_states(), 50, axis=0)
    batch = np.concatenate([reps, vecs], axis=0)
    auto, full = canon_pair(model, refine_rounds=rounds)
    fa = np.asarray(auto.fingerprints(batch))
    fb = np.asarray(full.fingerprints(batch))
    assert np.array_equal(fa, fb)
    assert not np.any(fa == U64_MAX)


def test_refinement_depth_preserves_partition():
    # deeper refinement only shrinks tie groups WITHIN an orbit: the
    # induced equality partition over a reachable sample must not move
    # (values may — the admissible-set minimum changes representative)
    model, _oracle, _states, vecs = states_of("raft5", depth=3, cap=120)
    parts = []
    for rounds in (1, 2, 3):
        auto, _ = canon_pair(model, refine_rounds=rounds)
        fps = np.asarray(auto.fingerprints(vecs)).tolist()
        first = {}
        parts.append([first.setdefault(fp, i) for i, fp in enumerate(fps)])
    assert parts[0] == parts[1] == parts[2]


def test_tie_group_local_lanes_exercised_and_bit_identical():
    # the tie-group-local tier must actually fire (lanes whose largest
    # tie group is >= 3 but not all-tied) alongside full-table lanes,
    # and both routes must match brute force lane-for-lane
    model, _oracle, _states, vecs = states_of("raft5", depth=2, cap=120)
    reps = np.repeat(model.init_states(), 30, axis=0)
    batch = np.concatenate([vecs, reps], axis=0).astype(np.int32)
    auto, full = canon_pair(model)
    view = batch[:, : auto.VL]
    sig = auto._signatures(view)
    _fp, _sigma, _pat, is_local, is_full = auto._tier_pre(view, sig)
    is_local = np.asarray(is_local)
    is_full = np.asarray(is_full)
    assert is_local.sum() > 0, "no tie-group-local lanes in the sample"
    assert is_full.sum() > 0, "no full-table lanes in the sample"
    fa = np.asarray(auto.fingerprints(batch))
    fb = np.asarray(full.fingerprints(batch))
    assert np.array_equal(fa, fb)
    # the local route in particular (the new code path) is bit-identical
    assert np.array_equal(fa[is_local], fb[is_local])


@pytest.mark.parametrize("name", list(CASES))
def test_orbit_invariance(name):
    model, oracle, states, vecs = states_of(name)
    auto, _ = canon_pair(model)
    fps = np.asarray(auto.fingerprints(vecs))
    S = model.layout.n_servers
    rng = np.random.default_rng(7)
    sigmas = [list(rng.permutation(S)) for _ in range(4)]
    for sigma in sigmas:
        pvecs = np.stack(
            [model.encode(oracle.permute(st, sigma)) for st in states]
        )
        pfps = np.asarray(auto.fingerprints(pvecs))
        assert np.array_equal(fps, pfps), f"sigma={sigma}"


@pytest.mark.parametrize("name", ["raft3", "pull3"])
def test_signature_equivariance(name):
    # sig(perm(x))[sigma[i]] == sig(x)[i] for every reachable sample state
    model, oracle, states, vecs = states_of(name)
    auto, _ = canon_pair(model)
    S = model.layout.n_servers
    sig = np.asarray(auto._signatures(vecs[:, : auto.VL]))
    for sigma in itertools.permutations(range(S)):
        pvecs = np.stack(
            [model.encode(oracle.permute(st, list(sigma))) for st in states]
        )
        psig = np.asarray(auto._signatures(pvecs[:, : auto.VL]))
        assert np.array_equal(psig[:, list(sigma)], sig), f"sigma={sigma}"


@pytest.mark.parametrize("name", ["raft3", "raft5"])
def test_fp_equality_matches_oracle_canon(name):
    # fp equality <=> oracle canonical-view equality on a reachable sample
    model, oracle, states, vecs = states_of(name, depth=4, cap=200)
    auto, _ = canon_pair(model)
    fps = np.asarray(auto.fingerprints(vecs)).tolist()
    keys = [oracle.canon(st) for st in states]
    by_key, by_fp = {}, {}
    for fp, key in zip(fps, keys):
        assert by_key.setdefault(key, fp) == fp, "same view, different fp"
        assert by_fp.setdefault(fp, key) == key, "fp collision between views"


def test_bag_multiset_hash_slot_order_free():
    # two encodings of the same bag in different slot order must hash
    # identically (the v3 bag hash is a multiset hash, no slot sort)
    model, _oracle = raft3()
    auto, _ = canon_pair(model)
    vec = np.asarray(model.init_states()[0:1]).copy()
    # synthesize: swap two occupied message slots if present; Init has an
    # empty bag, so craft one state with two sends via the oracle
    _model, oracle2 = raft3()
    st = oracle2.init_state()
    for _lab, s2 in oracle2.successors(st):
        if len(s2["messages"]) >= 2:
            st = s2
            break
    else:  # walk two steps to get >=2 distinct records
        for _lab, s2 in oracle2.successors(st):
            for _lab2, s3 in oracle2.successors(s2):
                if len(s3["messages"]) >= 2:
                    st = s3
                    break
            if len(st["messages"]) >= 2:
                break
    assert len(st["messages"]) >= 2
    vec = model.encode(st)[None, :]
    # swap the first two occupied slots across all bag words + cnt
    lay = model.layout
    sls = [lay.sl(f.name) for f in lay.fields.values()
           if f.kind in ("msg_hi", "msg_lo", "msg_word", "msg_cnt")]
    swapped = vec.copy()
    for sl in sls:
        seg = swapped[:, sl].copy()
        seg[:, [0, 1]] = seg[:, [1, 0]]
        swapped[:, sl] = seg
    f1 = np.asarray(auto.fingerprints(vec))
    f2 = np.asarray(auto.fingerprints(swapped))
    assert np.array_equal(f1, f2)


def _fresh_memo(cap):
    return np.full((cap, 2), np.uint64(U64_MAX))


@pytest.mark.parametrize("name", ["raft3", "raft5"])
def test_memo_cold_equals_plain(name):
    # a cold (all-empty) memo pass computes every fingerprint through
    # the same tiered canon — bit-identical to the unmemoized entry
    model, _oracle, _states, vecs = states_of(name, depth=3, cap=80)
    reps = np.repeat(model.init_states(), 40, axis=0)
    batch = np.concatenate([vecs, reps, vecs], axis=0).astype(np.int32)
    auto, _ = canon_pair(model)
    valid = np.ones(len(batch), dtype=bool)
    plain = np.asarray(auto.fingerprints(batch))
    cold, memo1, n_hit = auto.fingerprints_memo(
        batch, valid, _fresh_memo(1 << 12))
    assert np.array_equal(np.asarray(cold), plain)
    assert int(n_hit) == 0

    # warm pass over the same batch: hits must return the SAME values
    warm, _memo2, n_hit2 = auto.fingerprints_memo(batch, valid, memo1)
    assert np.array_equal(np.asarray(warm), plain)
    assert int(n_hit2) > 0


def test_memo_invalid_lanes_masked():
    model, _oracle, _states, vecs = states_of("raft3", depth=3, cap=60)
    auto, _ = canon_pair(model)
    valid = np.arange(len(vecs)) % 3 != 0
    fps, _memo, _n = auto.fingerprints_memo(
        vecs.astype(np.int32), valid, _fresh_memo(1 << 10))
    fps = np.asarray(fps)
    assert np.all(fps[~valid] == U64_MAX)
    plain = np.asarray(auto.fingerprints(vecs))
    assert np.array_equal(fps[valid], plain[valid])


def test_memo_correct_across_eviction():
    # a 2-slot table under a few hundred distinct keys evicts on nearly
    # every insert; values must stay exactly the cold canon regardless —
    # eviction only costs recomputation, never correctness
    model, _oracle, _states, vecs = states_of("raft5", depth=3, cap=100)
    reps = np.repeat(model.init_states(), 20, axis=0)
    batch = np.concatenate([vecs, reps], axis=0).astype(np.int32)
    auto, _ = canon_pair(model)
    valid = np.ones(len(batch), dtype=bool)
    plain = np.asarray(auto.fingerprints(batch))
    memo = _fresh_memo(2)
    for _ in range(3):  # repeated passes churn the tiny table
        fps, memo, _n = auto.fingerprints_memo(batch, valid, memo)
        assert np.array_equal(np.asarray(fps), plain)


def test_seeded_family_differs():
    # the audit relies on seeded families failing independently: same
    # states, different seed => (near-certainly) different fingerprints
    model, _oracle, _states, vecs = states_of("raft3")
    a0 = Canonicalizer.for_model(model, symmetry=True, seed=0)
    a1 = Canonicalizer.for_model(model, symmetry=True, seed=0x5EED)
    f0 = np.asarray(a0.fingerprints(vecs))
    f1 = np.asarray(a1.fingerprints(vecs))
    assert not np.array_equal(f0, f1)
    # but both must induce the SAME partition (orbit separation)
    assert (len(set(f0.tolist())) == len(set(f1.tolist())))
