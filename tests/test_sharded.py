"""Sharded-frontier BFS on a virtual CPU mesh: exact count parity with the
oracle, and mesh-size robustness. Uses a 2-server model to keep the
shard_map compile small (the 3-server parity evidence lives in
test_checker.py's sequential runs)."""

import jax
import numpy as np
import pytest

from raft_tpu.models.raft import RaftParams, cached_model
from raft_tpu.oracle.raft_oracle import RaftOracle
from raft_tpu.parallel.sharded import ShardedBFS

PARAMS = RaftParams(n_servers=2, n_values=1, max_elections=2, max_restarts=0, msg_slots=16)


@pytest.mark.parametrize("ndev", [4, 8])
def test_sharded_counts_match_oracle(ndev):
    devices = jax.devices()[:ndev]
    model = cached_model(PARAMS)
    engine = ShardedBFS(
        model,
        invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry=True,
        devices=devices,
        chunk=512,
        frontier_cap=1024,
        seen_cap=1 << 12,
    )
    res = engine.run()
    oracle = RaftOracle(2, 1, 2, 0)
    ores = oracle.bfs(invariants=(), symmetry=True)
    assert res.violation_invariant is None
    assert res.distinct == ores["distinct"]
    assert res.depth == len(ores["depth_counts"]) - 1
    assert res.depth_counts == ores["depth_counts"]


def test_sharded_detects_violation():
    import jax.numpy as jnp

    model = cached_model(PARAMS)
    lay = model.layout

    def no_commit(states):
        return jnp.all(lay.get(states, "commitIndex") == 0, axis=1)

    model.invariants["NoCommit"] = no_commit
    try:
        engine = ShardedBFS(
            model,
            invariants=("NoCommit",),
            devices=jax.devices()[:4],
            chunk=512,
            frontier_cap=1024,
            seen_cap=1 << 12,
        )
        res = engine.run()
        assert res.violation_invariant == "NoCommit"
    finally:
        del model.invariants["NoCommit"]
