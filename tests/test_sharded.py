"""Sharded-frontier BFS v2 on a virtual CPU mesh: exact count parity with
the oracle (including frontier sub-stepping and capacity growth),
cross-shard counterexample traces, and mesh-size robustness. Uses small
models to keep the shard_map compiles fast; the deep 3-server exhaustion
evidence lives in __graft_entry__.dryrun_multichip (driver-run)."""

import jax
import numpy as np
import pytest

from raft_tpu.models.raft import RaftParams, cached_model
from raft_tpu.oracle.raft_oracle import RaftOracle
from raft_tpu.parallel.sharded import ShardedBFS

PARAMS = RaftParams(n_servers=2, n_values=1, max_elections=2, max_restarts=0, msg_slots=16)


@pytest.mark.slow
@pytest.mark.parametrize("ndev", [4, 8])
def test_sharded_counts_match_oracle(ndev):
    devices = jax.devices()[:ndev]
    model = cached_model(PARAMS)
    engine = ShardedBFS(
        model,
        invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry=True,
        devices=devices,
        chunk=512,
        frontier_cap=1024,
        seen_cap=1 << 12,
    )
    res = engine.run(collect_metrics=True)
    oracle = RaftOracle(2, 1, 2, 0)
    ores = oracle.bfs(invariants=(), symmetry=True)
    assert res.violation_invariant is None
    assert res.exhausted
    assert res.distinct == ores["distinct"]
    assert res.depth == len(ores["depth_counts"]) - 1
    assert res.depth_counts == ores["depth_counts"]
    # §5.5 metrics: all-to-all volume is reported per wave
    assert all("a2a_lanes" in m and "a2a_bytes" in m for m in res.metrics)
    assert sum(m["a2a_lanes"] for m in res.metrics) > 0


@pytest.mark.slow
def test_sharded_3server_nontoy_parity():
    """Non-toy sharded regression (round-4 verdict Weak #5): the 3-server
    MaxElections=1 space (~22k distinct, waves far wider than chunk) on a
    D=4 mesh must exhaust with counts identical to the single-device
    engine — route_cap/growth at real widths, not the 2-server toy."""
    from raft_tpu.checker.bfs import BFSChecker

    p3 = RaftParams(n_servers=3, n_values=1, max_elections=1,
                    max_restarts=0, msg_slots=24)
    model = cached_model(p3)
    engine = ShardedBFS(
        model,
        invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry=True,
        devices=jax.devices()[:4],
        chunk=512,
        frontier_cap=4096,
        seen_cap=1 << 14,
    )
    res = engine.run()
    ref = BFSChecker(model, invariants=(), symmetry=True, chunk=1024).run()
    assert res.violation_invariant is None
    assert res.exhausted and ref.exhausted
    assert res.distinct == ref.distinct
    assert res.depth_counts == ref.depth_counts


@pytest.mark.slow
def test_sharded_substep_and_growth_parity():
    """Tiny chunk + tiny initial caps force the sub-stepping cursor (wave
    frontier > chunk) AND between-wave buffer growth; counts must still be
    exact (round-2 verdict item 3: kill the one-chunk-per-wave cap)."""
    model = cached_model(PARAMS)
    engine = ShardedBFS(
        model,
        invariants=(),
        symmetry=True,
        devices=jax.devices()[:4],
        chunk=16,  # waves reach width ~100 per shard -> many sub-steps
        frontier_cap=32,
        seen_cap=1 << 8,
        journal_cap=1 << 8,
    )
    res = engine.run()
    ores = RaftOracle(2, 1, 2, 0).bfs(invariants=(), symmetry=True)
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]
    assert engine.FCAP > 32  # frontier growth actually ran (the
    # seen-set no longer grows a flat SCAP; its LSM adds levels instead)


@pytest.mark.slow
def test_sharded_detects_violation_with_trace():
    import jax.numpy as jnp

    model = cached_model(PARAMS)
    lay = model.layout

    def no_commit(states):
        return jnp.all(lay.get(states, "commitIndex") == 0, axis=1)

    model.invariants["NoCommit"] = no_commit
    try:
        engine = ShardedBFS(
            model,
            invariants=("NoCommit",),
            devices=jax.devices()[:4],
            chunk=512,
            frontier_cap=1024,
            seen_cap=1 << 12,
        )
        res = engine.run()
        assert res.violation_invariant == "NoCommit"
        # v2: the sharded path reconstructs the counterexample trace by
        # walking cross-shard (shard, lgid) parent pointers and replaying
        # (replay asserts each journalled candidate is enabled)
        assert res.trace is not None and len(res.trace) >= 2
        assert res.trace[0][0] == "Initial predicate"
        final = res.trace[-1][1]
        assert any(ci > 0 for ci in final["commitIndex"])
    finally:
        del model.invariants["NoCommit"]


@pytest.mark.slow
def test_sharded_checkpoint_resume(tmp_path):
    """Split a sharded run at a depth cap via checkpoint, resume in a
    FRESH engine, and require exact parity (distinct/depth_counts/total/
    terminal) with an uninterrupted run — including the per-shard LSM
    re-seeding and the gen/term/routed *_base offset bookkeeping."""
    model = cached_model(RaftParams(n_servers=2, n_values=1,
                                    max_elections=2, max_restarts=1,
                                    msg_slots=16))
    invs = ("LeaderHasAllAckedValues", "NoLogDivergence")
    kw = dict(invariants=invs, devices=jax.devices()[:4], chunk=128,
              frontier_cap=1024, seen_cap=4096)
    ref = ShardedBFS(model, **kw).run()
    ck = str(tmp_path / "sh.npz")
    r1 = ShardedBFS(model, **kw).run(max_depth=6, checkpoint_path=ck,
                                     checkpoint_every_s=0.0)
    assert not r1.exhausted and r1.depth == 6
    r2 = ShardedBFS(model, **kw).run(resume=ck)
    assert r2.exhausted
    assert r2.distinct == ref.distinct
    assert list(r2.depth_counts) == list(ref.depth_counts)
    assert r2.total == ref.total
    assert r2.terminal == ref.terminal


def test_reshard_smoke_d2_to_d1(tmp_path):
    """Tier-1 elastic-mesh smoke on the CPU mesh: a D=2 checkpoint
    resumes on a D=1 mesh via the load-time fp%D re-route, with exact
    oracle parity; reshard=False refuses with a message naming both
    mesh sizes."""
    from raft_tpu.obs import Telemetry

    p = RaftParams(n_servers=2, n_values=1, max_elections=1,
                   max_restarts=0, msg_slots=16)
    model = cached_model(p)
    kw = dict(invariants=("NoLogDivergence",), symmetry=True, chunk=256,
              frontier_cap=1024, seen_cap=1 << 12)
    ck = str(tmp_path / "sh.npz")
    r1 = ShardedBFS(model, devices=jax.devices()[:2], **kw).run(
        max_depth=2, checkpoint_path=ck, checkpoint_every_s=0.0)
    assert r1.depth == 2
    eng1 = ShardedBFS(model, devices=jax.devices()[:1], **kw)
    # refusal: fails fast in check_spec, before the D=1 precompile
    with pytest.raises(ValueError) as ei:
        eng1.run(resume=ck, reshard=False)
    assert "D=2 mesh" in str(ei.value) and "D=1" in str(ei.value)
    tel = Telemetry()
    res = eng1.run(resume=ck, max_depth=4, telemetry=tel)
    ores = RaftOracle(2, 1, 1, 0).bfs(invariants=(), symmetry=True,
                                      max_depth=4)
    assert res.distinct == ores["distinct"]
    assert list(res.depth_counts) == list(ores["depth_counts"])
    resh = [e for e in tel.events if e["event"] == "reshard"]
    assert len(resh) == 1
    assert resh[0]["from_d"] == 2 and resh[0]["to_d"] == 1
    assert resh[0]["depth"] == 2


@pytest.mark.slow
def test_sharded_checkpoint_mesh_portable(tmp_path):
    """Checkpoints are mesh-portable: the payload carries per-shard
    sorted-fingerprint segments (D is provenance, not identity), so a
    D=4 checkpoint resumes on D=2 and D=1 with counts bit-identical to
    the uninterrupted D=4 run — the preemptible-mesh story."""
    model = cached_model(PARAMS)
    kw = dict(invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
              symmetry=True, chunk=128, frontier_cap=1024, seen_cap=4096)
    ref = ShardedBFS(model, devices=jax.devices()[:4], **kw).run()
    ck = str(tmp_path / "sh.npz")
    r1 = ShardedBFS(model, devices=jax.devices()[:4], **kw).run(
        max_depth=4, checkpoint_path=ck, checkpoint_every_s=0.0)
    assert not r1.exhausted
    for ndev in (2, 1):
        res = ShardedBFS(model, devices=jax.devices()[:ndev], **kw).run(
            resume=ck)
        assert res.exhausted, ndev
        assert res.distinct == ref.distinct, ndev
        assert list(res.depth_counts) == list(ref.depth_counts), ndev
        assert res.total == ref.total and res.terminal == ref.terminal
        # enabled/fired tallies are mesh-invariant; the new-state column
        # credits whichever action's successor won the dedup race, and
        # that tie-break legitimately depends on shard routing order
        # (true of unbroken runs at different D too) — so compare its
        # total, not its per-action split
        cov_r, cov_n = np.asarray(ref.coverage), np.asarray(res.coverage)
        assert (cov_r[:, :2] == cov_n[:, :2]).all(), ndev
        assert cov_r[:, 2].sum() == cov_n[:, 2].sum(), ndev


@pytest.mark.slow
def test_sharded_reshard_preserves_violation_trace(tmp_path):
    """A resharded resume must find the same violation at the same depth
    with a replay-valid counterexample of the same length — parent
    pointers survive the owner re-route."""
    import jax.numpy as jnp

    model = cached_model(PARAMS)
    lay = model.layout

    def no_commit(states):
        return jnp.all(lay.get(states, "commitIndex") == 0, axis=1)

    model.invariants["NoCommit"] = no_commit
    try:
        kw = dict(invariants=("NoCommit",), chunk=512, frontier_cap=1024,
                  seen_cap=1 << 12)
        ref = ShardedBFS(model, devices=jax.devices()[:4], **kw).run()
        assert ref.violation_invariant == "NoCommit"
        ck = str(tmp_path / "sh.npz")
        ShardedBFS(model, devices=jax.devices()[:4], **kw).run(
            max_depth=2, checkpoint_path=ck, checkpoint_every_s=0.0)
        res = ShardedBFS(model, devices=jax.devices()[:2], **kw).run(
            resume=ck)
        assert res.violation_invariant == "NoCommit"
        assert res.depth == ref.depth
        # trace replay asserts every journalled candidate is enabled, so
        # reaching here proves the resharded parent chain is real
        assert len(res.trace) == len(ref.trace)
        final = res.trace[-1][1]
        assert any(ci > 0 for ci in final["commitIndex"])
    finally:
        del model.invariants["NoCommit"]


@pytest.mark.slow
def test_sharded_ovf_abort_spills_wave_start_checkpoint(tmp_path):
    """A capacity abort now spills a redistributable wave-start
    checkpoint (LSM subtraction via the jfp lane) before raising, so a
    grown resume loses zero work — parity with DeviceBFS."""
    from raft_tpu.resilience import (
        CapacityOverflow, ChaosInjector, ChaosSpec,
    )

    model = cached_model(PARAMS)
    kw = dict(invariants=(), chunk=128, frontier_cap=1024, seen_cap=4096)
    ref = ShardedBFS(model, devices=jax.devices()[:4], **kw).run(
        max_depth=5)
    ck = str(tmp_path / "sh.npz")
    eng = ShardedBFS(model, devices=jax.devices()[:4], **kw)
    chaos = ChaosInjector(ChaosSpec.parse("ovf=3"))
    with pytest.raises(CapacityOverflow) as ei:
        eng.run(max_depth=5, checkpoint_path=ck, checkpoint_every_s=1e9,
                chaos=chaos)
    assert ei.value.checkpoint_saved
    assert "wave-start checkpoint saved" in str(ei.value)
    growth = eng.grow_for_overflow(ei.value.bits)
    assert growth  # the spurious bit is the growable frontier bit
    res = ShardedBFS(model, devices=jax.devices()[:4],
                     **{**kw, **growth}).run(resume=ck, max_depth=5)
    assert res.distinct == ref.distinct
    assert list(res.depth_counts) == list(ref.depth_counts)
    assert res.total == ref.total and res.terminal == ref.terminal
