"""Sharded-frontier BFS v2 on a virtual CPU mesh: exact count parity with
the oracle (including frontier sub-stepping and capacity growth),
cross-shard counterexample traces, and mesh-size robustness. Uses small
models to keep the shard_map compiles fast; the deep 3-server exhaustion
evidence lives in __graft_entry__.dryrun_multichip (driver-run)."""

import jax
import numpy as np
import pytest

from raft_tpu.models.raft import RaftParams, cached_model
from raft_tpu.oracle.raft_oracle import RaftOracle
from raft_tpu.parallel.sharded import ShardedBFS

PARAMS = RaftParams(n_servers=2, n_values=1, max_elections=2, max_restarts=0, msg_slots=16)


@pytest.mark.slow
@pytest.mark.parametrize("ndev", [4, 8])
def test_sharded_counts_match_oracle(ndev):
    devices = jax.devices()[:ndev]
    model = cached_model(PARAMS)
    engine = ShardedBFS(
        model,
        invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry=True,
        devices=devices,
        chunk=512,
        frontier_cap=1024,
        seen_cap=1 << 12,
    )
    res = engine.run(collect_metrics=True)
    oracle = RaftOracle(2, 1, 2, 0)
    ores = oracle.bfs(invariants=(), symmetry=True)
    assert res.violation_invariant is None
    assert res.exhausted
    assert res.distinct == ores["distinct"]
    assert res.depth == len(ores["depth_counts"]) - 1
    assert res.depth_counts == ores["depth_counts"]
    # §5.5 metrics: all-to-all volume is reported per wave
    assert all("a2a_lanes" in m and "a2a_bytes" in m for m in res.metrics)
    assert sum(m["a2a_lanes"] for m in res.metrics) > 0


@pytest.mark.slow
def test_sharded_3server_nontoy_parity():
    """Non-toy sharded regression (round-4 verdict Weak #5): the 3-server
    MaxElections=1 space (~22k distinct, waves far wider than chunk) on a
    D=4 mesh must exhaust with counts identical to the single-device
    engine — route_cap/growth at real widths, not the 2-server toy."""
    from raft_tpu.checker.bfs import BFSChecker

    p3 = RaftParams(n_servers=3, n_values=1, max_elections=1,
                    max_restarts=0, msg_slots=24)
    model = cached_model(p3)
    engine = ShardedBFS(
        model,
        invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry=True,
        devices=jax.devices()[:4],
        chunk=512,
        frontier_cap=4096,
        seen_cap=1 << 14,
    )
    res = engine.run()
    ref = BFSChecker(model, invariants=(), symmetry=True, chunk=1024).run()
    assert res.violation_invariant is None
    assert res.exhausted and ref.exhausted
    assert res.distinct == ref.distinct
    assert res.depth_counts == ref.depth_counts


@pytest.mark.slow
def test_sharded_substep_and_growth_parity():
    """Tiny chunk + tiny initial caps force the sub-stepping cursor (wave
    frontier > chunk) AND between-wave buffer growth; counts must still be
    exact (round-2 verdict item 3: kill the one-chunk-per-wave cap)."""
    model = cached_model(PARAMS)
    engine = ShardedBFS(
        model,
        invariants=(),
        symmetry=True,
        devices=jax.devices()[:4],
        chunk=16,  # waves reach width ~100 per shard -> many sub-steps
        frontier_cap=32,
        seen_cap=1 << 8,
        journal_cap=1 << 8,
    )
    res = engine.run()
    ores = RaftOracle(2, 1, 2, 0).bfs(invariants=(), symmetry=True)
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]
    assert engine.FCAP > 32  # frontier growth actually ran (the
    # seen-set no longer grows a flat SCAP; its LSM adds levels instead)


@pytest.mark.slow
def test_sharded_detects_violation_with_trace():
    import jax.numpy as jnp

    model = cached_model(PARAMS)
    lay = model.layout

    def no_commit(states):
        return jnp.all(lay.get(states, "commitIndex") == 0, axis=1)

    model.invariants["NoCommit"] = no_commit
    try:
        engine = ShardedBFS(
            model,
            invariants=("NoCommit",),
            devices=jax.devices()[:4],
            chunk=512,
            frontier_cap=1024,
            seen_cap=1 << 12,
        )
        res = engine.run()
        assert res.violation_invariant == "NoCommit"
        # v2: the sharded path reconstructs the counterexample trace by
        # walking cross-shard (shard, lgid) parent pointers and replaying
        # (replay asserts each journalled candidate is enabled)
        assert res.trace is not None and len(res.trace) >= 2
        assert res.trace[0][0] == "Initial predicate"
        final = res.trace[-1][1]
        assert any(ci > 0 for ci in final["commitIndex"])
    finally:
        del model.invariants["NoCommit"]


@pytest.mark.slow
def test_sharded_checkpoint_resume(tmp_path):
    """Split a sharded run at a depth cap via checkpoint, resume in a
    FRESH engine, and require exact parity (distinct/depth_counts/total/
    terminal) with an uninterrupted run — including the per-shard LSM
    re-seeding and the gen/term/routed *_base offset bookkeeping."""
    model = cached_model(RaftParams(n_servers=2, n_values=1,
                                    max_elections=2, max_restarts=1,
                                    msg_slots=16))
    invs = ("LeaderHasAllAckedValues", "NoLogDivergence")
    kw = dict(invariants=invs, devices=jax.devices()[:4], chunk=128,
              frontier_cap=1024, seen_cap=4096)
    ref = ShardedBFS(model, **kw).run()
    ck = str(tmp_path / "sh.npz")
    r1 = ShardedBFS(model, **kw).run(max_depth=6, checkpoint_path=ck,
                                     checkpoint_every_s=0.0)
    assert not r1.exhausted and r1.depth == 6
    r2 = ShardedBFS(model, **kw).run(resume=ck)
    assert r2.exhausted
    assert r2.distinct == ref.distinct
    assert list(r2.depth_counts) == list(ref.depth_counts)
    assert r2.total == ref.total
    assert r2.terminal == ref.terminal


@pytest.mark.slow
def test_sharded_checkpoint_mesh_mismatch(tmp_path):
    """A checkpoint is bound to its mesh size (fp%D ownership): resuming
    on a different D must be refused, not silently mis-shard."""
    model = cached_model(PARAMS)
    kw = dict(invariants=(), chunk=128, frontier_cap=1024, seen_cap=4096)
    ck = str(tmp_path / "sh.npz")
    ShardedBFS(model, devices=jax.devices()[:4], **kw).run(
        max_depth=4, checkpoint_path=ck, checkpoint_every_s=0.0)
    with pytest.raises(ValueError, match="checkpoint is for spec"):
        ShardedBFS(model, devices=jax.devices()[:2], **kw).run(resume=ck)
