"""Live telemetry (raft_tpu/obs): JSONL stream validity, zero extra
device syncs, watchdog, schema/renderer lock-step, fleet stats, CLI.

The headline guarantees pinned here:

  * the metrics stream is count-accurate — the final cumulative
    ``distinct`` in the wave stream equals the checker's reported
    distinct, at any cadence;
  * attaching a collector adds ZERO extra ``jax.device_get`` calls and
    leaves the result bit-identical (telemetry reuses the once-per-wave
    host snapshot the loop already fetches);
  * the progress renderer only consumes declared WAVE_KEYS, so the
    stderr line and the JSONL schema cannot drift apart.
"""

import io
import json

import pytest

from raft_tpu.obs import (
    DECLARED_EVENTS,
    MANIFEST_KEYS,
    STALL_KEYS,
    SUMMARY_KEYS,
    TIMELINE_STAGES,
    WAVE_KEYS,
    MetricsCollector,
    ProgressRenderer,
    Telemetry,
    format_count,
    hashv_of,
    validate_lines,
)
from raft_tpu.models.raft import RaftParams, cached_model

SMALL = RaftParams(
    n_servers=2, n_values=1, max_elections=1, max_restarts=0, msg_slots=16
)
INVS = ("LeaderHasAllAckedValues", "NoLogDivergence")


def _device(**kw):
    from raft_tpu.checker.device_bfs import DeviceBFS

    kw.setdefault("chunk", 256)
    kw.setdefault("frontier_cap", 1 << 12)
    kw.setdefault("seen_cap", 1 << 15)
    kw.setdefault("journal_cap", 1 << 15)
    return DeviceBFS(cached_model(SMALL), invariants=INVS, symmetry=True, **kw)


# ---------------------------------------------------------------- stream


def test_device_metrics_stream_valid_and_count_accurate(tmp_path):
    path = tmp_path / "m.jsonl"
    with Telemetry(metrics_path=str(path)) as tel:
        res = _device().run(max_depth=4, telemetry=tel)
    with open(path) as fh:
        lines = fh.readlines()
    counts, problems = validate_lines(lines)
    assert not problems, problems
    assert counts["manifest"] == 1 and counts["summary"] == 1
    assert counts["wave"] >= 4  # >= depth-many wave events
    # coverage pairs with each wave plus one final snapshot
    assert counts["coverage"] == counts["wave"] + 1

    events = [json.loads(ln) for ln in lines]
    assert events[0]["event"] == "manifest"
    assert events[-1]["event"] == "summary"
    man, summ = events[0], events[-1]
    waves = [e for e in events if e["event"] == "wave"]

    # every declared key present on every event
    for ev, keys in zip((man, waves[0], summ), (MANIFEST_KEYS, WAVE_KEYS, SUMMARY_KEYS)):
        assert all(k in ev for k in keys), (ev["event"], keys)

    # count-accuracy: cumulative distinct in the stream == result
    assert waves[-1]["distinct"] == res.distinct
    assert summ["distinct"] == res.distinct
    assert summ["total"] == res.total
    assert summ["depth"] == res.depth
    assert summ["exit_cause"] == "max_depth"
    assert summ["waves"] == len(waves)
    # wave index strictly increasing from 1
    assert [w["wave"] for w in waves] == list(range(1, len(waves) + 1))

    # manifest provenance: ident carries the fingerprint revision
    assert man["engine"] == "device"
    assert man["hashv"] == hashv_of(man["ident"]) > 0
    assert man["symmetry"] is True


def test_cadence_keeps_stream_count_accurate(tmp_path):
    path = tmp_path / "m2.jsonl"
    with Telemetry(metrics_path=str(path), every=3) as tel:
        res = _device().run(max_depth=5, telemetry=tel)
    with open(path) as fh:
        lines = fh.readlines()
    _, problems = validate_lines(lines)
    assert not problems, problems
    waves = [json.loads(ln) for ln in lines if '"wave"' in ln]
    waves = [w for w in waves if w["event"] == "wave"]
    assert 0 < len(waves) < 5  # thinned by cadence...
    # ...but the LAST wave is always flushed so the tail stays accurate
    assert waves[-1]["distinct"] == res.distinct


def test_telemetry_adds_zero_device_syncs_and_is_bit_identical(monkeypatch):
    import jax

    eng = _device()
    eng.run(max_depth=4)  # warm the compile cache outside the count

    real = jax.device_get
    calls = {"n": 0}

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    bare = eng.run(max_depth=4)
    n_bare = calls["n"]

    calls["n"] = 0
    tel = Telemetry()
    instrumented = eng.run(max_depth=4, telemetry=tel)
    n_tel = calls["n"]
    monkeypatch.undo()

    assert n_tel == n_bare, (
        f"telemetry added {n_tel - n_bare} device_get syncs per run"
    )
    assert instrumented.distinct == bare.distinct
    assert instrumented.depth_counts == bare.depth_counts
    assert instrumented.total == bare.total
    assert instrumented.terminal == bare.terminal
    assert len(tel.wave_events()) >= 4
    # the per-action coverage block rides the same snapshot: present,
    # bit-identical with telemetry on/off, final event mirrors it
    assert bare.coverage is not None
    assert instrumented.coverage == bare.coverage
    covs = tel.coverage_events()
    assert covs and covs[-1]["final"] is True
    assert covs[-1]["actions"] == instrumented.coverage


# -------------------------------------------------------------- watchdog


def _fields(keys, **kw):
    """All declared keys zeroed except event/wave (the collector owns
    those), overridden by kw."""
    ev = dict.fromkeys(keys, 0)
    ev.pop("event", None)
    ev.pop("wave", None)
    ev.update(kw)
    return ev


def _wave(depth, wave_s):
    return _fields(WAVE_KEYS, depth=depth, wave_s=wave_s)


def test_watchdog_flags_stall_against_prior_median():
    c = MetricsCollector(stall_factor=4.0, stall_min_waves=5)
    c.manifest(_fields(MANIFEST_KEYS))
    for d in range(5):
        c.wave(_wave(d, 1.0))
    assert c.stalls == 0
    c.wave(_wave(5, 10.0))  # 10x the rolling median of 1.0
    assert c.stalls == 1
    stall = c.events_of("stall")[0]
    assert all(k in stall for k in STALL_KEYS)
    assert stall["factor"] == pytest.approx(10.0)
    assert stall["median_wave_s"] == pytest.approx(1.0)
    # judged BEFORE joining the window: an immediate second slow wave
    # still compares against the healthy median
    c.wave(_wave(6, 10.0))
    assert c.stalls == 2
    c.summary(_fields(SUMMARY_KEYS))
    assert c.last_summary["stalls"] == 2

    # too few samples -> never fires (no median to trust yet)
    c2 = MetricsCollector(stall_min_waves=5)
    c2.manifest(_fields(MANIFEST_KEYS))
    for d in range(4):
        c2.wave(_wave(d, 1.0))
    c2.wave(_wave(4, 50.0))
    assert c2.stalls == 0


# -------------------------------------------- schema/renderer lock-step


def test_schema_and_renderer_stay_in_sync():
    # the contract check_metrics_schema.py and the engines share
    assert tuple(n for n, _ in DECLARED_EVENTS) == (
        "manifest", "wave", "stall", "coverage", "summary",
        "retry", "resume", "ckpt_generation", "preempt",
        "shard_lost", "reshard", "shard_stall",
        "timeline", "memwatch", "shard_wave",
    )
    for _, keys in DECLARED_EVENTS:
        assert keys[0] == "event"
        assert len(set(keys)) == len(keys)
    # the renderer may only read declared wave keys
    assert set(ProgressRenderer.CONSUMES) <= set(WAVE_KEYS)

    ev = dict.fromkeys(WAVE_KEYS, 0)
    ev.update(event="wave", depth=7, generated_total=1_200_000,
              distinct=310_000, distinct_per_s=2648.0,
              canon_memo_hit_rate=0.71)
    line = ProgressRenderer().render_wave(ev)
    assert line == (
        "Progress (depth 7): 1.2M generated, 310k distinct, 2,648/s, "
        "memo 71%"
    )

    out = io.StringIO()
    r = ProgressRenderer(every_s=0.0, stream=out)
    r(ev)
    r({"event": "stall", "wave": 9, "depth": 7, "wave_s": 8.0,
       "median_wave_s": 1.0, "factor": 8.0})
    summ = dict.fromkeys(SUMMARY_KEYS, 0)
    summ.update(event="summary", exit_cause="exhausted", seconds=1.0)
    r(summ)
    text = out.getvalue()
    assert "Progress (depth 7)" in text
    assert "Warning: wave 9" in text
    assert "Finished" in text and "(exhausted)" in text


def test_format_count():
    assert format_count(1234) == "1,234"
    assert format_count(310_000) == "310k"
    assert format_count(1_200_000) == "1.2M"
    assert format_count(3_400_000_000) == "3.4B"


# --------------------------------------------------- schema validation


def test_check_metrics_schema_script(tmp_path):
    from scripts.check_metrics_schema import main, validate_file

    good = tmp_path / "good.jsonl"
    c = MetricsCollector(path=str(good))
    c.manifest(_fields(MANIFEST_KEYS, ident="x/hashv=5"))
    for d in range(3):
        c.wave(_wave(d, 0.5))
    c.summary(_fields(SUMMARY_KEYS, exit_cause="exhausted"))
    c.close()
    counts, problems = validate_file(str(good))
    assert not problems, problems
    assert counts == {"manifest": 1, "wave": 3, "summary": 1}
    assert main([str(good)]) == 0

    bad = tmp_path / "bad.jsonl"
    lines = good.read_text().splitlines()
    w1 = json.loads(lines[1])
    del w1["distinct"]  # missing declared key
    w1["wave"] = 7  # breaks strict increase for the next wave
    lines[1] = json.dumps(w1)
    bad.write_text("\n".join(lines) + "\n{not json\n")
    _, problems = validate_file(str(bad))
    text = "\n".join(problems)
    assert "missing declared keys" in text
    assert "strictly" in text
    assert "not valid JSON" in text
    assert main([str(bad)]) == 1

    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    _, problems = validate_file(str(empty))
    assert any("empty stream" in p for p in problems)
    assert main([]) == 64


def test_double_buffered_write_lags_by_one(tmp_path):
    path = tmp_path / "buf.jsonl"
    c = MetricsCollector(path=str(path))
    c.manifest(_fields(MANIFEST_KEYS))
    c.wave(_wave(0, 0.1))
    c._fh.flush()
    on_disk = path.read_text().splitlines()
    assert len(on_disk) == 1  # wave 1 still pending; manifest flushed
    c.close()
    assert len(path.read_text().splitlines()) == 2


# ----------------------------------------------------- engines: others


@pytest.mark.slow
def test_host_checker_stream(tmp_path):
    from raft_tpu.checker.bfs import BFSChecker

    path = tmp_path / "host.jsonl"
    with Telemetry(metrics_path=str(path)) as tel:
        res = BFSChecker(
            cached_model(SMALL), invariants=INVS, symmetry=True, chunk=256
        ).run(telemetry=tel)
    with open(path) as fh:
        counts, problems = validate_lines(fh)
    assert not problems, problems
    waves = tel.wave_events()
    assert waves[-1]["distinct"] == res.distinct
    assert tel.last_summary["engine"] == "host"
    assert tel.last_summary["exit_cause"] == "exhausted"


@pytest.mark.slow
def test_sharded_stream_and_fleet_stats(tmp_path):
    import jax

    from raft_tpu.parallel.sharded import ShardedBFS

    path = tmp_path / "shard.jsonl"
    engine = ShardedBFS(
        cached_model(SMALL), invariants=INVS, symmetry=True,
        devices=jax.devices()[:4], chunk=512, frontier_cap=1024,
        seen_cap=1 << 12,
    )
    with Telemetry(metrics_path=str(path)) as tel:
        res = engine.run(telemetry=tel)
    with open(path) as fh:
        counts, problems = validate_lines(fh)
    assert not problems, problems
    assert counts["manifest"] == 1 and counts["summary"] == 1
    man = tel.events[0]
    assert man["engine"] == "sharded" and man["device_count"] == 4
    assert tel.wave_events()[-1]["distinct"] == res.distinct

    # satellite: aggregated fleet memo stats + per-shard skew on the
    # returned result
    assert res.stats is not None
    for k in ("canon_memo_hits", "canon_memo_hit_rate", "shard_memo_hits",
              "shard_distinct", "shard_skew", "coverage"):
        assert k in res.stats, k
    assert len(res.stats["shard_memo_hits"]) == 4
    assert sum(res.stats["shard_distinct"]) == res.distinct
    # fleet-summed coverage: one row per action, new sums to distinct
    # beyond the inits
    assert res.coverage == res.stats["coverage"]
    assert len(res.coverage) == len(cached_model(SMALL).ACTION_NAMES)
    assert sum(r[2] for r in res.coverage) == res.distinct - res.depth_counts[0]
    assert res.stats["shard_skew"] >= 1.0
    assert tel.last_summary["canon_memo_hit_rate"] == res.stats[
        "canon_memo_hit_rate"
    ]


# ------------------------------------------ wave-timeline observatory


def test_timeline_sampled_waves_bit_identical_device(tmp_path):
    """The tentpole contract on the device engine: --timeline re-runs
    every Nth wave as separately timed stage dispatches that compute
    bit-identical counts, and the stream carries the new events."""
    eng = _device()
    bare = eng.run(max_depth=5)

    path = tmp_path / "tl.jsonl"
    with Telemetry(metrics_path=str(path), timeline_every=2) as tel:
        res = eng.run(max_depth=5, telemetry=tel)

    assert res.distinct == bare.distinct
    assert res.total == bare.total
    assert res.terminal == bare.terminal
    assert res.depth_counts == bare.depth_counts

    with open(path) as fh:
        counts, problems = validate_lines(fh)
    assert not problems, problems
    assert counts["timeline"] >= 2
    assert counts["memwatch"] >= 1

    tls = [e for e in tel.events if e["event"] == "timeline"]
    for tl in tls:
        assert tl["every"] == 2
        assert set(tl["stages"]) <= set(TIMELINE_STAGES)
        assert sum(tl["stages"].values()) > 0
        assert tl["wave_s"] >= 0

    # every wave (sampled or not) carries the host-side phase split
    for w in tel.wave_events():
        for k in ("device_s", "host_s", "ckpt_s", "tel_s"):
            assert isinstance(w[k], (int, float)), k
            assert w[k] >= 0, k

    s = tel.last_summary
    assert s["timeline_every"] == 2
    assert s["timeline_waves"] == len(tls)
    assert s["hbm_peak_bytes"] > 0
    assert 0 < s["hbm_peak_frac"] < 1


def _small_kraft():
    from raft_tpu.models.kraft import KRaftParams
    from raft_tpu.models.kraft import cached_model as kraft_model

    return kraft_model(KRaftParams(
        n_servers=3, n_values=1, max_elections=1, max_restarts=0,
        msg_slots=40,
    ))


@pytest.mark.slow
@pytest.mark.parametrize("which", ["raft", "kraft"])
def test_timeline_parity_all_engines(which):
    """Sampled-wave bit-identity across the full engine matrix (2
    models x host/device/sharded) — the staged dispatch must never
    change what gets checked."""
    import jax

    from raft_tpu.checker.bfs import BFSChecker
    from raft_tpu.checker.device_bfs import DeviceBFS
    from raft_tpu.parallel.sharded import ShardedBFS

    if which == "raft":
        model, invs = cached_model(SMALL), INVS
    else:
        model = _small_kraft()
        invs = ("LeaderHasAllAckedValues", "NoLogDivergence",
                "NeverTwoLeadersInSameEpoch", "NoIllegalState")

    factories = {
        "host": lambda: BFSChecker(
            model, invariants=invs, symmetry=True, chunk=256),
        "device": lambda: DeviceBFS(
            model, invariants=invs, symmetry=True, chunk=256,
            frontier_cap=1 << 12, seen_cap=1 << 15, journal_cap=1 << 15),
        "sharded": lambda: ShardedBFS(
            model, invariants=invs, symmetry=True,
            devices=jax.devices()[:2], chunk=512, frontier_cap=2048,
            seen_cap=1 << 13),
    }
    for name, make in factories.items():
        bare = make().run(max_depth=5)
        tel = Telemetry(timeline_every=2)
        res = make().run(max_depth=5, telemetry=tel)
        assert res.distinct == bare.distinct, (which, name)
        assert res.total == bare.total, (which, name)
        assert res.depth_counts == bare.depth_counts, (which, name)
        tls = [e for e in tel.events if e["event"] == "timeline"]
        assert tls, (which, name)
        assert all(set(t["stages"]) <= set(TIMELINE_STAGES) for t in tls)


@pytest.mark.slow
def test_sharded_timeline_shard_wave_events(tmp_path):
    """Sharded D=2: sampled waves emit one shard_wave row per shard
    with work shares in [0,1]; the exchange-share gauge lands on the
    sampled wave events; obs_report renders the critical-path table."""
    import jax

    from raft_tpu.parallel.sharded import ShardedBFS

    path = tmp_path / "sw.jsonl"
    eng = ShardedBFS(
        cached_model(SMALL), invariants=INVS, symmetry=True,
        devices=jax.devices()[:2], chunk=512, frontier_cap=1024,
        seen_cap=1 << 12,
    )
    with Telemetry(metrics_path=str(path), timeline_every=2) as tel:
        eng.run(max_depth=6, telemetry=tel)

    with open(path) as fh:
        counts, problems = validate_lines(fh)
    assert not problems, problems

    tls = [e for e in tel.events if e["event"] == "timeline"]
    sws = [e for e in tel.events if e["event"] == "shard_wave"]
    assert tls and len(sws) == 2 * len(tls)  # one row per shard per sample
    by_wave: dict[int, list[dict]] = {}
    for sw in sws:
        assert sw["device_count"] == 2
        assert 0 <= sw["shard"] < 2
        assert 0.0 <= sw["work_share"] <= 1.0
        assert sw["routed_lanes"] >= 0 and sw["routed_bytes"] >= 0
        by_wave.setdefault(sw["wave"], []).append(sw)
    for wave, rows in by_wave.items():
        assert sorted(r["shard"] for r in rows) == [0, 1]
        if sum(r["new"] for r in rows) > 0:
            assert sum(r["work_share"] for r in rows) == pytest.approx(
                1.0, abs=0.01), wave

    shares = [
        w["exchange_share"] for w in tel.wave_events()
        if w["exchange_share"] is not None
    ]
    assert shares and all(0.0 <= s <= 1.0 for s in shares)

    from scripts.obs_report import render_run, split_runs

    with open(path) as fh:
        text = render_run(split_runs(fh)[-1])
    assert "Shard critical path" in text
    assert "shard skew" in text
    assert "Wave timeline" in text
    assert "Memory watermarks" in text


def test_progress_renderer_observatory_gauges():
    ev = dict.fromkeys(WAVE_KEYS, 0)
    ev.update(event="wave", depth=7, generated_total=100, distinct=50,
              distinct_per_s=10.0, canon_memo_hit_rate=0.5,
              exchange_share=0.25, hbm_frac=0.5)
    line = ProgressRenderer().render_wave(ev)
    assert line.endswith(", a2a 25%, hbm 50%")
    # null/zero gauges leave the pinned base line untouched
    ev.update(exchange_share=None, hbm_frac=0)
    assert ProgressRenderer().render_wave(ev).endswith("memo 50%")


# ---------------------------------------- observatory schema fixtures


def _observatory_stream(tmp_path, name="obs.jsonl"):
    """One schema-clean stream exercising all three new events."""
    path = tmp_path / name
    c = MetricsCollector(path=str(path))
    c.manifest(_fields(MANIFEST_KEYS, ident="x/hashv=5"))
    c.wave(_wave(0, 0.5))
    c.event("timeline", wave=1, depth=0, every=2,
            stages={"expand": 0.1, "emit": 0.05}, wave_s=0.5)
    c.event("memwatch", wave=1, depth=0, total_bytes=100, peak_bytes=100,
            budget_bytes=1000, frac=0.1, breakdown={"frontier": 60, "seen": 40})
    c.event("shard_wave", wave=1, depth=0, shard=1, device_count=2, new=5,
            routed_lanes=3, routed_bytes=120, work_share=0.5, shard_s=0.2,
            exchange_s=0.01, compute_s=0.2)
    c.wave(_wave(1, 0.4))
    c.event("memwatch", wave=2, depth=1, total_bytes=150, peak_bytes=200,
            budget_bytes=1000, frac=0.2, breakdown={"frontier": 150})
    c.summary(_fields(SUMMARY_KEYS, exit_cause="exhausted"))
    c.close()
    return path


def _perturb(path, tmp_path, match, repl, name):
    lines = path.read_text().splitlines()
    hits = [i for i, ln in enumerate(lines) if match in ln]
    assert hits, match
    lines[hits[0]] = lines[hits[0]].replace(match, repl)
    bad = tmp_path / name
    bad.write_text("\n".join(lines) + "\n")
    return bad


def test_observatory_fixture_positive(tmp_path):
    from scripts.check_metrics_schema import validate_file

    good = _observatory_stream(tmp_path)
    counts, problems = validate_file(str(good))
    assert not problems, problems
    assert counts["timeline"] == 1
    assert counts["memwatch"] == 2
    assert counts["shard_wave"] == 1


def test_observatory_fixture_bad_stage_name(tmp_path):
    from scripts.check_metrics_schema import validate_file

    good = _observatory_stream(tmp_path)
    bad = _perturb(good, tmp_path, '"expand"', '"quux"', "bad_stage.jsonl")
    _, problems = validate_file(str(bad))
    assert any("stage names" in p and "quux" in p for p in problems), problems


def test_observatory_fixture_nonmonotone_peak(tmp_path):
    from scripts.check_metrics_schema import validate_file

    good = _observatory_stream(tmp_path)
    # second memwatch peak drops below the first: 200 -> 50
    bad = _perturb(good, tmp_path, '"peak_bytes": 200', '"peak_bytes": 50',
                   "bad_peak.jsonl")
    # keep total <= peak so ONLY the monotonicity rule fires
    bad.write_text(bad.read_text().replace('"total_bytes": 150',
                                           '"total_bytes": 50'))
    _, problems = validate_file(str(bad))
    assert any("monotone" in p for p in problems), problems


def test_observatory_fixture_shard_out_of_range(tmp_path):
    from scripts.check_metrics_schema import validate_file

    good = _observatory_stream(tmp_path)
    bad = _perturb(good, tmp_path, '"shard": 1', '"shard": 2',
                   "bad_shard.jsonl")
    _, problems = validate_file(str(bad))
    assert any("out of range" in p for p in problems), problems


# ------------------------------------------------------------ bench gate


def test_bench_gate_evaluate():
    from scripts.bench_gate import evaluate

    summ = {"event": "summary", "distinct": 31, "total": 40, "depth": 4,
            "terminal": 0, "seconds": 10.0}
    base = {"metrics": {
        "distinct": {"value": 31, "direction": "eq"},
        "seconds": {"value": 8.0, "rel_tol": 0.5, "direction": "max"},
    }}
    v = evaluate(summ, base)
    assert v["pass"] and v["checked"] == 2 and not v["failures"]

    tight = {"metrics": {"distinct": {"value": 25, "direction": "eq"}}}
    v2 = evaluate(summ, tight)
    assert not v2["pass"]
    assert "distinct" in v2["failures"][0]

    # a gated metric missing from the summary fails, never skips
    v3 = evaluate(summ, {"metrics": {"nope": {"value": 1}}})
    assert not v3["pass"] and "missing" in v3["failures"][0]

    # min direction: smaller is worse
    v4 = evaluate(summ, {"metrics": {
        "seconds": {"value": 20.0, "rel_tol": 0.1, "direction": "min"}}})
    assert not v4["pass"]

    # malformed baselines raise (exit 64 at the CLI), distinct from fail
    for bad in (
        {"metrics": {}},
        {"metrics": {"x": {"value": 1, "tol": 1, "rel_tol": 1}}},
        {"metrics": {"x": {"value": 1, "direction": "sideways"}}},
        {"metrics": {"x": {}}},
    ):
        with pytest.raises(ValueError):
            evaluate(summ, bad)


def test_bench_gate_script_exit_codes(tmp_path, capsys):
    from scripts.bench_gate import main as gate_main

    summ = {"event": "summary", "distinct": 31, "depth": 4}
    m = tmp_path / "m.jsonl"
    m.write_text(json.dumps(summ) + "\n")
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"metrics": {
        "distinct": {"value": 31, "direction": "eq"}}}))
    assert gate_main([str(m), str(base)]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["pass"] is True and verdict["checked"] == 1

    tight = tmp_path / "tight.json"
    tight.write_text(json.dumps({"metrics": {
        "distinct": {"value": 25, "direction": "eq"}}}))
    assert gate_main([str(m), str(tight)]) == 3
    cap = capsys.readouterr()
    assert json.loads(cap.out)["pass"] is False
    assert "GATE FAIL" in cap.err

    assert gate_main([str(tmp_path / "nope.jsonl"), str(base)]) == 66
    capsys.readouterr()
    broken = tmp_path / "broken.json"
    broken.write_text("{not json")
    assert gate_main([str(m), str(broken)]) == 64
    capsys.readouterr()


# ----------------------------------------------------------------- CLI


CFG = """\
CONSTANTS
    n1 = n1
    n2 = n2
    v1 = v1
    Server = { n1, n2 }
    Value = { v1 }
    Follower = Follower
    Candidate = Candidate
    Leader = Leader
    Nil = Nil
    RequestVoteRequest = RequestVoteRequest
    RequestVoteResponse = RequestVoteResponse
    AppendEntriesRequest = AppendEntriesRequest
    AppendEntriesResponse = AppendEntriesResponse
    EqualTerm = EqualTerm
    LessOrEqualTerm = LessOrEqualTerm
    MaxElections = 1
    MaxRestarts = 0

INIT Init
NEXT Next

INVARIANT
NoLogDivergence
"""

CLI_BASE = [
    "--platform", "cpu", "--msg-slots", "16", "--max-depth", "4",
    "--chunk", "256", "--frontier-cap", "4096", "--seen-cap", "16384",
    "--journal-cap", "16384",
]


@pytest.mark.slow
def test_cli_json_progress_and_bit_identical_result(tmp_path, capsys):
    from raft_tpu.__main__ import main

    cfg = tmp_path / "Raft.cfg"
    cfg.write_text(CFG)
    mpath = tmp_path / "cli.jsonl"

    rc = main([str(cfg), *CLI_BASE, "--progress=0",
               "--metrics-out", str(mpath), "--json"])
    cap = capsys.readouterr()
    assert rc == 0, cap.err

    # stdout: result lines only, summary event as the LAST line
    out_lines = cap.out.strip().splitlines()
    summ = json.loads(out_lines[-1])
    assert summ["event"] == "summary"
    assert summ["exit_cause"] == "max_depth"
    result_line = next(ln for ln in out_lines if ln.startswith("distinct="))
    assert f"distinct={summ['distinct']}" in result_line

    # stderr: banner + live progress, never stdout
    assert "spec=" in cap.err
    assert "Progress (depth" in cap.err
    assert "Progress (depth" not in cap.out

    # the file on disk is schema-clean and count-accurate
    with open(mpath) as fh:
        counts, problems = validate_lines(fh)
    assert not problems, problems
    assert counts["wave"] >= 4

    # telemetry must not perturb the result: identical result line
    # without any telemetry flag
    rc = main([str(cfg), *CLI_BASE])
    cap = capsys.readouterr()
    assert rc == 0, cap.err
    bare_line = next(
        ln for ln in cap.out.strip().splitlines()
        if ln.startswith("distinct=")
    )
    # wall-clock fields differ run to run; the counts must not
    strip = lambda s: s.split(" time=")[0]  # noqa: E731
    assert strip(bare_line) == strip(result_line)


CFG3 = CFG.replace("    v1 = v1", "    n3 = n3\n    v1 = v1").replace(
    "Server = { n1, n2 }", "Server = { n1, n2, n3 }")


def test_cli_timeline_smoke_and_bench_gate(tmp_path, capsys):
    """Tier-1 smoke of the whole observatory loop: a depth-4 3-server
    Raft CLI check under --timeline=2 produces a schema-clean stream
    that PASSES the committed bench_gate baseline, while a 20%-tighter
    baseline fails with the strict-gate exit code 3."""
    from pathlib import Path

    from raft_tpu.__main__ import main
    from scripts.bench_gate import main as gate_main
    from scripts.check_metrics_schema import validate_file

    cfg = tmp_path / "Raft.cfg"
    cfg.write_text(CFG3)
    mpath = tmp_path / "tl.jsonl"

    rc = main([str(cfg), *CLI_BASE, "--timeline=2",
               "--metrics-out", str(mpath)])
    cap = capsys.readouterr()
    assert rc == 0, cap.err

    counts, problems = validate_file(str(mpath))
    assert not problems, problems
    assert counts["wave"] == 4
    assert counts["timeline"] == 2  # waves at depth 1 and 3
    assert counts["memwatch"] >= 1

    with open(mpath) as fh:
        summ = json.loads(fh.read().strip().splitlines()[-1])
    assert summ["event"] == "summary"
    assert summ["timeline_every"] == 2
    assert summ["timeline_waves"] == 2
    assert summ["hbm_peak_bytes"] > 0

    golden = Path(__file__).parent / "golden" / "raft3_depth4_gate.json"
    assert gate_main([str(mpath), str(golden)]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["pass"] is True and verdict["checked"] >= 4

    # tighten every eq count by 20%: a regression gate that cannot
    # fail is no gate — pin the exit-3 path on the same stream
    base = json.loads(golden.read_text())
    base["metrics"]["distinct"]["value"] = round(
        base["metrics"]["distinct"]["value"] * 0.8)
    tight = tmp_path / "tight.json"
    tight.write_text(json.dumps(base))
    assert gate_main([str(mpath), str(tight)]) == 3
    cap = capsys.readouterr()
    assert "GATE FAIL distinct" in cap.err
