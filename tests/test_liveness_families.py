"""Liveness formulas for the non-core spec families: KRaft
ValuesNotStuck (KRaft.tla:867-879) and both reconfig specs'
ReconfigurationCompletes (JointConsensus :1039-1054, AddRemove
:990-1005, which its own comment says to run with MaxElections = 0)."""

import jax
import numpy as np
import pytest

from raft_tpu.checker.liveness import LivenessChecker


@pytest.mark.slow
def test_kraft_values_not_stuck_matches_oracle_brute_force():
    from raft_tpu.models.kraft import KRaftParams, LEADER, cached_model
    from raft_tpu.oracle.kraft_oracle import KRaftOracle

    m = cached_model(KRaftParams(2, 1, 1, 0, msg_slots=16))
    res = LivenessChecker(m, ("ValuesNotStuck",), chunk=256).run()

    o = KRaftOracle(2, 1, 1, 0)
    init = o.init_state()
    seen = {o.serialize_full(init): 0}
    states = [init]
    edges = []
    i = 0
    while i < len(states):
        for _lab, s2 in o.successors(states[i]):
            k = o.serialize_full(s2)
            if k not in seen:
                seen[k] = len(states)
                states.append(s2)
            edges.append((i, seen[k]))
        i += 1
    assert res.distinct == len(states)
    assert res.total_edges == len(edges)

    import collections

    out = collections.defaultdict(list)
    for s, t in edges:
        out[s].append(t)

    def q(st, v):
        # oracle state: tuple-valued per-server fields, int counters,
        # state names as small-int enums matching the device model
        if st["electionCtr"] == o.max_elections and not any(
            x == LEADER for x in st["state"]
        ):
            return True
        has = [any(e[1] == v for e in lg) for lg in st["log"]]
        return all(has) or not any(has)

    in_s = [not q(st, 0) for st in states]
    changed = True
    while changed:
        changed = False
        for g in range(len(states)):
            if in_s[g] and out[g] and not any(in_s[t] for t in out[g]):
                in_s[g] = False
                changed = True
    assert (res.violation is not None) == any(in_s)


@pytest.mark.slow
def test_reconfig_add_remove_completes_clean():
    """AddRemove ReconfigurationCompletes holds with MaxElections = 0
    (the spec's own prescribed mode for this property, :988)."""
    from raft_tpu.models.reconfig_raft import ReconfigRaftParams, cached_model

    m = cached_model(ReconfigRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=0,
        max_restarts=0, max_values_per_term=1, max_add_reconfigs=1,
        max_remove_reconfigs=0, min_cluster_size=2, max_cluster_size=3,
        msg_slots=32,
    ))
    res = LivenessChecker(m, ("ReconfigurationCompletes",), chunk=256).run()
    assert res.violation is None
    assert res.distinct > 100  # the add-reconfig flow really explored


@pytest.mark.skip(
    reason="the round-4 vectorized graph build removed the old 10-min "
    "host-dict bottleneck, but the joint spec's kernels blow up LLVM "
    "('Cannot allocate memory', exit 139) when the liveness checker "
    "compiles them at its batch shapes on this host's CPU backend — "
    "reproduced at chunk 2048/512/256. The formula kernels are covered "
    "by the spot-check test below and the machinery by the AddRemove "
    "full proof above; run the joint proof on a host whose XLA CPU "
    "build survives the compile (or on device)"
)
def test_joint_completes_clean():
    from raft_tpu.models.joint_raft import JointRaftParams, cached_model

    m = cached_model(JointRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=0,
        max_restarts=0, max_reconfigs=1, max_values_per_term=1,
        reconfig_type=3, msg_slots=40,
    ))
    res = LivenessChecker(m, ("ReconfigurationCompletes",), chunk=256).run()
    assert res.violation is None
    assert res.distinct > 100


def test_reconfig_p_q_kernels_on_known_states():
    """Kernel spot checks: the pre-installed init (leader + committed
    InitClusterCommand replicated to all members) satisfies both the
    antecedent and the consequent of AddRemove ReconfigurationCompletes."""
    from raft_tpu.models.reconfig_raft import ReconfigRaftParams, cached_model

    m = cached_model(ReconfigRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=0,
        max_restarts=0, max_values_per_term=1, max_add_reconfigs=1,
        max_remove_reconfigs=0, min_cluster_size=2, max_cluster_size=3,
        msg_slots=32,
    ))
    init = np.asarray(m.init_states())
    _label, p_fn, q_fn = m.liveness["ReconfigurationCompletes"][0]
    p = np.asarray(jax.device_get(p_fn(init)))
    q = np.asarray(jax.device_get(q_fn(init)))
    assert p.all() and q.all()


def test_joint_p_kernel_requires_committed_oldnew():
    """Joint's antecedent needs a COMMITTED OldNewConfigCommand: false at
    init (only a NewConfigCommand is seeded, :341-354)."""
    from raft_tpu.models.joint_raft import JointRaftParams, cached_model

    m = cached_model(JointRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=0,
        max_restarts=0, max_reconfigs=1, max_values_per_term=1,
        reconfig_type=1, msg_slots=40,
    ))
    init = np.asarray(m.init_states())
    _label, p_fn, q_fn = m.liveness["ReconfigurationCompletes"][0]
    assert not np.asarray(jax.device_get(p_fn(init))).any()
    # the carve-out/majority consequent holds at init (leader exists and
    # there is no committed OldNew entry to contradict it: Q quantifies
    # existentially, so with no OldNew committed it is FALSE unless the
    # carve-out fires; with a live leader it must be False)
    assert not np.asarray(jax.device_get(q_fn(init))).any()


@pytest.mark.slow
def test_kraft_reconfig_liveness_clean():
    """KRaftWithReconfig ValuesNotStuck + ReconfigurationNotStuck on a
    tiny no-reconfig universe (spec :1810-1839; NoProgressPossible's
    state-vs-role quirk reproduced, see _no_progress_possible)."""
    from raft_tpu.models.kraft_reconfig import KRaftReconfigParams, cached_model

    m = cached_model(KRaftReconfigParams(
        n_hosts=2, n_values=1, init_cluster_size=2, min_cluster_size=2,
        max_cluster_size=2, max_elections=1, max_restarts=0,
        max_values_per_epoch=1, max_add_reconfigs=1, max_remove_reconfigs=1,
        max_spawned_servers=2, msg_slots=24,  # fixed universe: 428 states
    ))
    res = LivenessChecker(
        m, ("ValuesNotStuck", "ReconfigurationNotStuck"), chunk=256
    ).run()
    assert res.violation is None, (
        res.violation.prop, res.violation.instance, res.violation.terminal
    )
    assert res.distinct > 300


def test_joint_q_majority_arm_on_constructed_state():
    """Drive the joint consequent's majority arm (:1027-1037) both ways
    with a hand-built state: a committed OldNewConfigCommand whose NEW
    member set has (a) a majority and (b) only a minority of self-aware,
    active members holding the matching NewConfigCommand."""
    from raft_tpu.models.joint_raft import (
        CMD_NEW, CMD_OLDNEW, LEADER, JointRaftParams, cached_model,
    )

    m = cached_model(JointRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=0,
        max_restarts=0, max_reconfigs=1, max_values_per_term=1,
        reconfig_type=1, msg_slots=40,
    ))
    lay = m.layout
    _label, p_fn, q_fn = m.liveness["ReconfigurationCompletes"][0]

    def put(vec, name, slot, val):
        vec[lay.fields[name].offset + slot] = val

    def put_lane(vec, name, slot, lane, val):
        vec[lay.fields[name].offset + slot * m.p.max_log + lane] = val

    def build(holders):
        """Leader 0 with OldNew(cid=2, new={0,1,2}) committed at index 2;
        `holders` = servers that carry the matching NewConfigCommand."""
        vec = np.asarray(m.init_states())[0].copy()
        put(vec, "state", 0, LEADER)
        put(vec, "currentTerm", 0, 1)
        put_lane(vec, "log_cmd", 0, 1, CMD_OLDNEW)
        put_lane(vec, "log_term", 0, 1, 1)
        put_lane(vec, "log_cid", 0, 1, 2)
        put_lane(vec, "log_old", 0, 1, 0b011)
        put_lane(vec, "log_new", 0, 1, 0b111)
        put(vec, "log_len", 0, 2)
        put(vec, "commitIndex", 0, 2)
        for j in holders:
            put_lane(vec, "log_cmd", j, 2, CMD_NEW)
            put_lane(vec, "log_term", j, 2, 1)
            put_lane(vec, "log_cid", j, 2, 2)
            put_lane(vec, "log_new", j, 2, 0b111)
            lay_len = lay.fields["log_len"].offset + j
            vec[lay_len] = max(vec[lay_len], 3)
            # self-aware member of its own config
            cm = lay.fields["config_members"].offset + j
            vec[cm] = vec[cm] | (1 << j)
        return vec[None]

    majority = build(holders=(0, 1))  # 2 of 3 new members
    minority = build(holders=(0,))  # 1 of 3
    assert np.asarray(jax.device_get(p_fn(majority))).all()
    assert np.asarray(jax.device_get(q_fn(majority))).all()
    assert np.asarray(jax.device_get(p_fn(minority))).all()
    assert not np.asarray(jax.device_get(q_fn(minority))).any()
