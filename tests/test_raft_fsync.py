"""RaftFsync differential tests: fsync-variant kernels vs the variant
oracle across policy combinations, BFS count parity, and reference-cfg
loading (raft-and-fsync/RaftFsync.tla + RaftFsync.cfg)."""

import numpy as np
import pytest

from pathlib import Path

import jax

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.models.raft import RaftModel, RaftParams, cached_model
from raft_tpu.oracle.raft_oracle import oracle_for

from conftest import collect_states as _collect_states


def fsync_params(before_ae: bool, quorum: bool, follower: bool, **kw) -> RaftParams:
    return RaftParams(
        n_servers=3,
        n_values=1,
        max_elections=kw.pop("max_elections", 1),
        max_restarts=kw.pop("max_restarts", 1),
        msg_slots=kw.pop("msg_slots", 24),
        strict_send_once=True,
        has_pending_response=False,
        trunc_term_mismatch=True,
        has_fsync=True,
        fsync_leader_before_ae=before_ae,
        fsync_leader_quorum=quorum,
        fsync_follower_reply=follower,
        **kw,
    )


# The reference cfg's policy (RaftFsync.cfg:24-26) plus the two extremes.
POLICIES = [(False, True, True), (False, False, False), (True, True, True)]


@pytest.mark.parametrize("policy", POLICIES)
def test_fsync_successor_sets_match_oracle(policy):
    params = fsync_params(*policy)
    model = cached_model(params)
    oracle = oracle_for(params)
    states = _collect_states(oracle, max_depth=6, cap=140)
    vecs = np.stack([model.encode(st) for st in states])
    succs, valid, rank, ovf = jax.device_get(model.expand(vecs))
    assert not np.any(valid & ovf)
    for b, st in enumerate(states):
        got = sorted(
            oracle.serialize_full(model.decode(succs[b, a]))
            for a in range(model.A)
            if valid[b, a]
        )
        want = sorted(oracle.serialize_full(s2) for _l, s2 in oracle.successors(st))
        assert got == want, f"successor mismatch at state {b} (policy {policy})"


def test_fsync_encode_decode_roundtrip():
    params = fsync_params(False, True, True)
    model = cached_model(params)
    oracle = oracle_for(params)
    for st in _collect_states(oracle, max_depth=5, cap=100):
        assert model.decode(model.encode(st)) == st


@pytest.mark.slow
def test_fsync_bfs_counts_match_oracle():
    params = fsync_params(False, True, True, max_elections=2, max_restarts=0)
    model = cached_model(params)
    oracle = oracle_for(params)
    invs = ("LeaderHasAllAckedValues", "NoLogDivergence")
    checker = BFSChecker(model, invariants=invs, symmetry=True, chunk=256)
    res = checker.run(max_depth=9)
    ores = oracle.bfs(invariants=invs, symmetry=True, max_depth=9)
    assert res.violation is None and ores["violation"] is None
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]


def test_fsync_restart_truncates_to_fsync_index():
    """Crash-restart data loss: log beyond fsyncIndex vanishes
    (RaftFsync.tla:211-216)."""
    params = fsync_params(False, False, False, max_restarts=1)
    oracle = oracle_for(params)
    st = oracle.init_state()
    st = dict(
        st,
        state=(2, 0, 0),  # leader
        log=(((1, 0),), (), ()),
        fsyncIndex=(0, 0, 0),
    )
    s2 = oracle.restart(st, 0)
    assert s2["log"][0] == ()  # fsyncIndex 0 -> empty log
    st2 = dict(st, fsyncIndex=(1, 0, 0))
    s3 = oracle.restart(st2, 0)
    assert s3["log"][0] == ((1, 0),)  # fsynced entry survives
    model = cached_model(params)
    for probe in (st, st2):
        vec = model.encode(probe)
        succs, valid, rank, _ = jax.device_get(model.expand(vec[None]))
        restart_cand = 0  # Restart(0) is binding 0
        assert valid[0, restart_cand]
        got = model.decode(succs[0, restart_cand])
        want = oracle.restart(probe, 0)
        assert oracle.serialize_full(got) == oracle.serialize_full(want)


@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_reference_fsync_cfg_loads():
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    cfg = parse_cfg("/root/reference/specifications/raft-and-fsync/RaftFsync.cfg")
    setup = build_from_cfg(cfg, msg_slots=16)
    p = setup.model.p
    assert setup.model.name == "RaftFsync"
    assert p.has_fsync and not p.fsync_leader_before_ae
    assert p.fsync_leader_quorum and p.fsync_follower_reply
    assert p.max_elections == 2 and p.max_restarts == 0
    assert setup.invariants == ("LeaderHasAllAckedValues", "NoLogDivergence")
    assert setup.symmetry
