"""Temporal-property checking under WF_vars(Next) (checker/liveness.py).

Differential ground truth: an independent oracle-graph brute force of the
same fair-behavior semantics (infinite path = lasso, terminal = fair
stutter), plus planted violations that must produce decodable lassos.
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.checker.liveness import LivenessChecker
from raft_tpu.models.raft import RaftParams, cached_model
from raft_tpu.oracle.raft_oracle import RaftOracle

SMALL = RaftParams(n_servers=2, n_values=1, max_elections=2, max_restarts=0, msg_slots=16)


def _oracle_graph(o):
    init = o.init_state()
    seen = {o.serialize_full(init): 0}
    states = [init]
    edges = []
    i = 0
    while i < len(states):
        for _lab, s2 in o.successors(states[i]):
            k = o.serialize_full(s2)
            if k not in seen:
                seen[k] = len(states)
                states.append(s2)
            edges.append((i, seen[k]))
        i += 1
    return states, edges


def _oracle_sustain(states, edges, notq):
    import collections

    out = collections.defaultdict(list)
    for s, t in edges:
        out[s].append(t)
    in_s = list(notq)
    changed = True
    while changed:
        changed = False
        for g in range(len(states)):
            if in_s[g] and out[g] and not any(in_s[t] for t in out[g]):
                in_s[g] = False
                changed = True
    return in_s


def test_values_not_stuck_matches_oracle_brute_force():
    """ValuesNotStuck on the 2-server model: the device full-state graph
    and violation verdict must match an independent oracle-side check of
    the same WF semantics (Raft.tla:545-576)."""
    m = cached_model(SMALL)
    res = LivenessChecker(m, ("ValuesNotStuck",), chunk=256).run()
    o = RaftOracle(2, 1, 2, 0)
    states, edges = _oracle_graph(o)

    def q(st, v):
        if st["electionCtr"] == o.max_elections and not any(
            x == "Leader" for x in st["state"]
        ):
            return True
        has = [any(e[1] == v for e in st["log"][i]) for i in range(2)]
        return all(has) or not any(has)

    sustain = _oracle_sustain(states, edges, [not q(st, 0) for st in states])
    assert res.distinct == len(states)
    assert res.total_edges == len(edges)
    assert (res.violation is not None) == any(sustain)
    assert res.violation is None  # ValuesNotStuck holds on this config


def test_planted_gf_violation_yields_lasso():
    """[]<>(no value anywhere) is false once a value commits and sticks:
    the checker must find it and decode a Q-free lasso/stutter."""
    m = cached_model(SMALL)
    lay = m.layout

    def never_any_value(states):
        lv = lay.get(states, "log_value")
        return jnp.all(lv == 0, axis=(1, 2))

    m.liveness["NeverAnyValue"] = [("v1", None, jax.jit(never_any_value))]
    try:
        res = LivenessChecker(m, ("NeverAnyValue",), chunk=256).run()
    finally:
        del m.liveness["NeverAnyValue"]
    v = res.violation
    assert v is not None and v.prop == "NeverAnyValue"
    assert v.prefix[0][0] == "Initial predicate"
    # the sustained suffix really avoids Q: the last prefix state (and the
    # whole loop, if any) must contain a value in some log
    tail_states = [v.prefix[-1][1]] + [st for _a, st in v.cycle]
    for st in tail_states:
        # decoded entries are (term, value) pairs; any entry is a value
        assert any(len(lg) > 0 for lg in st["log"])


def test_planted_leadsto_violation_exercises_p_path():
    """(leader exists) ~> FALSE must be violated, with the prefix reaching
    a state where P holds (the leads-to P != None code path)."""
    m = cached_model(SMALL)
    lay = m.layout
    from raft_tpu.models.raft import LEADER

    def has_leader(states):
        return jnp.any(lay.get(states, "state") == LEADER, axis=1)

    def never(states):
        return jnp.zeros(states.shape[:-1], dtype=bool)

    m.liveness["LeaderDoom"] = [("", jax.jit(has_leader), jax.jit(never))]
    try:
        res = LivenessChecker(m, ("LeaderDoom",), chunk=256).run()
    finally:
        del m.liveness["LeaderDoom"]
    v = res.violation
    assert v is not None
    # P (a leader exists) holds at the start of the sustained suffix —
    # somewhere on the prefix (the stem then continues inside ~Q); the
    # decoded state field carries the numeric enum
    assert any(
        any(s == LEADER for s in st["state"]) for _a, st in v.prefix
    )


def test_unknown_property_refused():
    m = cached_model(SMALL)
    with pytest.raises(ValueError, match="no liveness support"):
        LivenessChecker(m, ("NoSuchProperty",))


def _run_cli(cfg_text, tmp_path, *extra):
    cfg = tmp_path / "Raft.cfg"
    cfg.write_text(cfg_text)
    return subprocess.run(
        [sys.executable, "-m", "raft_tpu", str(cfg), "--platform", "cpu",
         "--msg-slots", "16", *extra],
        capture_output=True, text=True, timeout=900,
    )


RAFT_LIVE_CFG = """\
CONSTANTS
    n1 = n1
    n2 = n2
    v1 = v1
    Server = { n1, n2 }
    Value = { v1 }
    Follower = Follower
    Candidate = Candidate
    Leader = Leader
    Nil = Nil
    RequestVoteRequest = RequestVoteRequest
    RequestVoteResponse = RequestVoteResponse
    AppendEntriesRequest = AppendEntriesRequest
    AppendEntriesResponse = AppendEntriesResponse
    EqualTerm = EqualTerm
    LessOrEqualTerm = LessOrEqualTerm
    MaxElections = 1
    MaxRestarts = 0

INIT Init
NEXT Next

PROPERTY
ValuesNotStuck

INVARIANT
NoLogDivergence
"""


@pytest.mark.slow
def test_cli_property_clean_pass(tmp_path):
    """Raft spec with PROPERTY ValuesNotStuck enabled: safety BFS then a
    clean liveness pass over the full-state graph."""
    r = _run_cli(RAFT_LIVE_CFG, tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no temporal property violations" in r.stdout


def test_cli_property_refusals(tmp_path):
    # unknown property -> refused, not dropped
    r = _run_cli(RAFT_LIVE_CFG.replace("ValuesNotStuck", "NoSuchProp"), tmp_path)
    assert r.returncode == 64
    assert "no liveness support" in r.stderr
    # partial exploration -> refused (liveness needs the full graph)
    r = _run_cli(RAFT_LIVE_CFG, tmp_path, "--max-depth", "3")
    assert r.returncode == 64
    assert "unsound" in r.stderr
    # oracle backend -> refused
    r = _run_cli(RAFT_LIVE_CFG, tmp_path, "--checker", "oracle")
    assert r.returncode == 64
