"""Differential tests: the JAX Raft kernels vs the pure-Python oracle.

The oracle (raft_tpu/oracle/raft_oracle.py) is written directly against the
TLA+ text; the kernels are an independent lowering. Agreement on successor
sets over every reachable state of a small model is the core correctness
evidence (SURVEY.md §4: differential testing strategy).
"""

import numpy as np
import pytest

import jax

from raft_tpu.models.raft import RaftModel, RaftParams
from raft_tpu.oracle.raft_oracle import RaftOracle
from raft_tpu.ops.symmetry import Canonicalizer

from conftest import collect_states as _collect_states


def make(params: RaftParams):
    model = RaftModel(params)
    oracle = RaftOracle(
        params.n_servers, params.n_values, params.max_elections, params.max_restarts
    )
    return model, oracle


SMALL = RaftParams(n_servers=3, n_values=1, max_elections=1, max_restarts=1, msg_slots=24)


def test_init_roundtrip():
    model, oracle = make(SMALL)
    vec = model.init_states()[0]
    assert model.decode(vec) == oracle.init_state()
    assert np.array_equal(model.encode(oracle.init_state()), vec)


def test_encode_decode_roundtrip_reachable():
    model, oracle = make(SMALL)
    for st in _collect_states(oracle, max_depth=4, cap=120):
        vec = model.encode(st)
        assert model.decode(vec) == st


def test_successor_sets_match_oracle():
    model, oracle = make(SMALL)
    states = _collect_states(oracle, max_depth=5, cap=150)
    vecs = np.stack([model.encode(st) for st in states])
    succs, valid, rank, ovf = jax.device_get(model.expand(vecs))
    assert not np.any(valid & ovf), "bag overflow on valid successor"
    for b, st in enumerate(states):
        got = sorted(
            oracle.serialize_full(model.decode(succs[b, a]))
            for a in range(model.A)
            if valid[b, a]
        )
        want = sorted(oracle.serialize_full(s2) for _l, s2 in oracle.successors(st))
        assert got == want, f"successor mismatch at state {b}: {st}"


def test_successor_counts_match_exactly():
    # valid-candidate multiplicity must equal the oracle's enabled-action count
    model, oracle = make(SMALL)
    states = _collect_states(oracle, max_depth=4, cap=80)
    vecs = np.stack([model.encode(st) for st in states])
    _, valid, _, _ = jax.device_get(model.expand(vecs))
    for b, st in enumerate(states):
        assert int(valid[b].sum()) == len(oracle.successors(st))


def test_fingerprint_permutation_invariance():
    model, oracle = make(SMALL)
    canon = Canonicalizer(model.layout, model.packer, symmetry=True)
    states = _collect_states(oracle, max_depth=4, cap=60)
    vecs = np.stack([model.encode(st) for st in states])
    fps = np.asarray(canon.fingerprints(vecs))
    perms = [[1, 0, 2], [2, 1, 0], [1, 2, 0]]
    for sigma in perms:
        pvecs = np.stack([model.encode(oracle.permute(st, sigma)) for st in states])
        pfps = np.asarray(canon.fingerprints(pvecs))
        assert np.array_equal(fps, pfps)


def test_fingerprint_matches_oracle_equivalence():
    # fp equality <=> oracle canonical-view equality, over a reachable sample
    model, oracle = make(SMALL)
    canon = Canonicalizer(model.layout, model.packer, symmetry=True)
    states = _collect_states(oracle, max_depth=4, cap=120)
    vecs = np.stack([model.encode(st) for st in states])
    fps = np.asarray(canon.fingerprints(vecs)).tolist()
    keys = [oracle.canon(st) for st in states]
    by_key = {}
    by_fp = {}
    for fp, key in zip(fps, keys):
        assert by_key.setdefault(key, fp) == fp, "same view, different fp"
        assert by_fp.setdefault(fp, key) == key, "fp collision between views"


def test_invariants_match_oracle():
    model, oracle = make(SMALL)
    states = _collect_states(oracle, max_depth=5, cap=150)
    vecs = np.stack([model.encode(st) for st in states])
    for name in ("NoLogDivergence", "LeaderHasAllAckedValues", "CommittedEntriesReachMajority"):
        ok = np.asarray(model.invariants[name](vecs))
        for b, st in enumerate(states):
            assert bool(ok[b]) == oracle.INVARIANTS[name](oracle, st), (name, b)
