"""The documented split-brain data-loss regression (SURVEY.md §4.7).

standard-raft/README.md:86-150 walks a concrete history for the
add/remove spec in which an administrator brings a removed server back
blank-but-same-identity (``ResetWithSameIdentity``, enabled in
``RaftWithReconfigAddRemove.tla:965``); a stale unreachable server then
wins an election with the blank server's vote and becomes a newest
leader missing an acknowledged value. This test replays that history
step by step through the oracle (servers mapped: README s3 -> 0 [leader],
s1 -> 1 [unreachable], s2 -> 2 [disk failure], s4 -> 3, s5 -> 4) and
asserts that LeaderHasAllAckedValues catches it — in the oracle AND in
the TPU invariant kernel on the encoded violating state."""

import numpy as np

import jax

from raft_tpu.models.reconfig_raft import ReconfigRaftParams, cached_model
from raft_tpu.oracle.reconfig_oracle import LEADER, NOTMEMBER, ReconfigRaftOracle

PARAMS = ReconfigRaftParams(
    n_servers=5, n_values=1, init_cluster_size=3, max_elections=1,
    max_restarts=0, max_values_per_term=1, max_add_reconfigs=2,
    max_remove_reconfigs=2, min_cluster_size=2, max_cluster_size=5,
    msg_slots=48,
)


def test_add_remove_split_brain_loses_acked_value():
    o = ReconfigRaftOracle(5, 1, 3, 1, 0, 1, 2, 2, 2, 5)
    st = o.init_state()

    def step(prefix, pick=None):
        nonlocal st
        for label, s2 in o.successors(st):
            if label.startswith(prefix) and (pick is None or pick(s2)):
                st = s2
                return
        raise AssertionError(f"no successor matching {prefix!r}")

    # commit a client value on the initial cluster (majority {0, 2};
    # server 1 is 'unreachable' and never receives it)
    step("ClientRequest(0,0)")
    step("AppendEntries(0,2)")
    step("AcceptAppendEntriesRequest")
    step("HandleAppendEntriesResponse")
    step("AdvanceCommitIndex(0)")
    assert st["acked"][0] is True

    # reconfig 1a: add server 3 (README step 1), snapshot catch-up
    step("AppendAddServerCommandToLog(0,3)")
    step("SendSnapshot(0,3)")
    step("UpdateTerm", pick=lambda s: s["currentTerm"][3] == 1)
    step("HandleSnapshotRequest")
    step("HandleSnapshotResponse")
    step("AppendEntries(0,2)")
    step("AcceptAppendEntriesRequest")
    step("HandleAppendEntriesResponse")
    step("AdvanceCommitIndex(0)")
    assert st["config"][0] == (2, frozenset({0, 1, 2, 3}), True)

    # reconfig 1b: remove the unreachable server 1 (README step 2)
    step("AppendRemoveServerCommandToLog(0,1)")
    for peer in (2, 3):
        step(f"AppendEntries(0,{peer})")
        step("AcceptAppendEntriesRequest")
        step("HandleAppendEntriesResponse")
    step("AdvanceCommitIndex(0)")
    assert st["config"][0] == (3, frozenset({0, 2, 3}), True)

    # reconfig 2a: add server 4 (README step 3)
    step("AppendAddServerCommandToLog(0,4)")
    step("SendSnapshot(0,4)")
    step("UpdateTerm", pick=lambda s: s["currentTerm"][4] == 1)
    step("HandleSnapshotRequest")
    step("HandleSnapshotResponse")
    for peer in (2, 3):
        step(f"AppendEntries(0,{peer})")
        step("AcceptAppendEntriesRequest")
        step("HandleAppendEntriesResponse")
    step("AdvanceCommitIndex(0)")
    assert st["config"][0] == (4, frozenset({0, 2, 3, 4}), True)

    # reconfig 2b: remove the failed server 2 (README step 4)
    step("AppendRemoveServerCommandToLog(0,2)")
    for peer in (3, 4):
        step(f"AppendEntries(0,{peer})")
        step("AcceptAppendEntriesRequest")
        step("HandleAppendEntriesResponse")
    step("AdvanceCommitIndex(0)")
    assert st["config"][0] == (5, frozenset({0, 3, 4}), True)

    # README step 5: server 2 is brought back blank with the same identity
    step("ResetWithSameIdentity(2)")
    assert st["state"][2] == NOTMEMBER and st["log"][2] == ()

    # README step 6: the stale server 1 (still on config 1) campaigns and
    # wins with the blank server 2's vote -> split brain
    step("RequestVote(1)")
    step("UpdateTerm", pick=lambda s: s["currentTerm"][2] == 2)
    step("HandleRequestVoteRequest", pick=lambda s: s["votedFor"][2] == 1)
    step("HandleRequestVoteResponse")
    step("BecomeLeader(1)")
    assert st["state"][1] == LEADER and st["state"][0] == LEADER  # split brain

    # the newest leader (term 2) is missing the acknowledged value
    assert not o.leader_has_all_acked_values(st)
    # the TPU invariant kernel must flag the same state
    model = cached_model(PARAMS)
    vec = model.encode(st)[None, :]
    ok = np.asarray(
        jax.device_get(model.invariants["LeaderHasAllAckedValues"](vec))
    )
    assert not ok[0]
    # sanity: the state also still diverges nowhere below common commit
    assert o.no_log_divergence(st)
