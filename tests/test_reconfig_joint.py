"""RaftWithReconfigJointConsensus differential tests: TPU kernels vs the
independent oracle (standard-raft/RaftWithReconfigJointConsensus.tla,
1,145 lines), dual-quorum flow, adjacency invariant, and reference-cfg
loading."""

import numpy as np
import pytest

from pathlib import Path

import jax

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.models.joint_raft import (
    JointRaftModel,
    JointRaftParams,
    cached_model,
    reconfig_shapes,
)
from raft_tpu.oracle.joint_oracle import LEADER, JointRaftOracle

from conftest import collect_states as _collect_states


def oracle_for(p: JointRaftParams) -> JointRaftOracle:
    return JointRaftOracle(
        p.n_servers, p.n_values, p.init_cluster_size, p.max_elections,
        p.max_restarts, p.max_reconfigs, p.max_values_per_term, p.reconfig_type,
    )


PARAMS = [
    # one-for-one swap (the reference cfg's ReconfigType=2), 3 servers
    JointRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=1,
        max_restarts=0, max_reconfigs=1, max_values_per_term=1,
        reconfig_type=2, msg_slots=64,
    ),
    # add-only on 3 servers
    JointRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=1,
        max_restarts=0, max_reconfigs=1, max_values_per_term=1,
        reconfig_type=3, msg_slots=64,
    ),
]


def test_reconfig_shapes_match_reconfig_type():
    """IsValidReconfiguration (:813-825) per type."""
    # type 2: exactly one added and one removed
    shapes2 = reconfig_shapes(3, 2)
    assert all(bin(a).count("1") == 1 and bin(r).count("1") == 1 for a, r in shapes2)
    assert len(shapes2) == 9
    # type 3: nonempty add, empty remove
    shapes3 = reconfig_shapes(3, 3)
    assert all(a != 0 and r == 0 for a, r in shapes3)
    assert len(shapes3) == 7
    # type 4: empty add, nonempty remove
    shapes4 = reconfig_shapes(3, 4)
    assert all(a == 0 and r != 0 for a, r in shapes4)
    # type 1: anything with at least one nonempty side
    shapes1 = reconfig_shapes(3, 1)
    assert len(shapes1) == 8 * 8 - 1


@pytest.mark.parametrize("params", PARAMS)
def test_successor_sets_match_oracle(params):
    model = cached_model(params)
    oracle = oracle_for(params)
    states = _collect_states(oracle, max_depth=8, cap=100)
    vecs = np.stack([model.encode(st) for st in states])
    succs, valid, rank, ovf = jax.device_get(model.expand(vecs))
    assert not np.any(valid & ovf)
    for b, st in enumerate(states):
        got = sorted(
            oracle.serialize_full(model.decode(succs[b, a]))
            for a in range(model.A)
            if valid[b, a]
        )
        want = sorted(oracle.serialize_full(s2) for _l, s2 in oracle.successors(st))
        assert got == want, f"successor mismatch at state {b}"


def test_encode_decode_roundtrip():
    params = PARAMS[0]
    model = cached_model(params)
    oracle = oracle_for(params)
    for st in _collect_states(oracle, max_depth=7, cap=90):
        assert model.decode(model.encode(st)) == st


@pytest.mark.slow
def test_bfs_counts_match_oracle():
    params = PARAMS[0]
    model = cached_model(params)
    oracle = oracle_for(params)
    invs = (
        "LeaderHasAllAckedValues",
        "NoLogDivergence",
        "MaxOneReconfigurationAtATime",
    )
    checker = BFSChecker(model, invariants=invs, symmetry=True, chunk=256)
    res = checker.run(max_depth=7)
    ores = oracle.bfs(invariants=invs, symmetry=True, max_depth=7)
    assert res.violation is None and ores["violation"] is None
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]
    assert res.total == ores["total"]


def test_joint_consensus_two_phase_flow():
    """Protocol sanity: OldNew (joint, dual quorum) -> commit -> New ->
    commit completes the reconfiguration (:827-876)."""
    params = PARAMS[0]  # swap: members {0,1}, swap 1 out for 2
    oracle = oracle_for(params)
    st = oracle.init_state()

    def step(prefix):
        nonlocal st
        for label, s2 in oracle.successors(st):
            if label.startswith(prefix):
                st = s2
                return
        raise AssertionError(f"no successor matching {prefix!r}")

    assert st["state"][0] == LEADER
    step("AppendOldNewConfigToLog(0,+[2],-[1])")
    cfg = st["config"][0]
    assert cfg[1] is True  # jointConsensus
    assert cfg[2] == frozenset({0, 1, 2})  # joint members = old + added
    assert cfg[3] == frozenset({0, 1})  # old
    assert cfg[4] == frozenset({0, 2})  # new
    assert st["nextIndex"][0][2] == -1  # fresh member needs a snapshot
    # catch up the fresh member via snapshot
    step("SendSnapshot(0,2)")
    step("UpdateTerm")
    step("HandleSnapshotRequest")
    step("HandleSnapshotResponse")
    # replicate the OldNew entry to member 1 and commit (dual quorum:
    # old={0,1} needs {0,1}-majority, new={0,2} needs {0,2}-majority)
    step("AppendEntries(0,1)")
    step("AcceptAppendEntriesRequest")
    step("HandleAppendEntriesResponse")
    step("AdvanceCommitIndex(0)")
    assert st["commitIndex"][0] == 2
    assert st["config"][0][5] is True  # committed, still joint
    assert st["config"][0][1] is True
    # phase 2: NewConfigCommand
    step("AppendNewConfigToLog(0)")
    assert st["config"][0][1] is False
    assert st["config"][0][2] == frozenset({0, 2})
    assert st["log"][0][-1][0] == "NewConfigCommand"
    assert oracle.max_one_reconfiguration_at_a_time(st)


def test_adjacency_invariant_detects_bad_log():
    """MaxOneReconfigurationAtATime (:1080-1101) rejects adjacent same-type
    config commands and accepts properly interleaved ones."""
    params = PARAMS[0]
    oracle = oracle_for(params)
    model = cached_model(params)
    st = oracle.init_state()
    members = frozenset({0, 1})
    # seed New at 1, then OldNew at 2, New at 3 (legal interleave)
    oldnew = ("OldNewConfigCommand", 1, (1, members, frozenset({0, 2}), frozenset({0, 1, 2})))
    new2 = ("NewConfigCommand", 1, (1, frozenset({0, 2})))
    good = oracle._with(
        st, log=oracle._set(st["log"], 0, st["log"][0] + (oldnew, new2))
    )
    assert oracle.max_one_reconfiguration_at_a_time(good)
    # two adjacent New commands (indices 1 and... seed New + another New)
    bad = oracle._with(
        st, log=oracle._set(st["log"], 0, st["log"][0] + (new2,))
    )
    assert not oracle.max_one_reconfiguration_at_a_time(bad)
    # the device invariant agrees on both
    vecs = np.stack([model.encode(good), model.encode(bad)])
    ok = np.asarray(
        jax.device_get(model.invariants["MaxOneReconfigurationAtATime"](vecs))
    )
    assert ok.tolist() == [True, False]


@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_reference_joint_cfg_loads():
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    path = (
        "/root/reference/specifications/standard-raft/"
        "RaftWithReconfigJointConsensus.cfg"
    )
    cfg = parse_cfg(path)
    setup = build_from_cfg(cfg, msg_slots=16)
    assert setup.model.name == "RaftWithReconfigJointConsensus"
    assert setup.model.p.n_servers == 4
    assert setup.model.p.init_cluster_size == 3
    assert setup.model.p.max_reconfigs == 2
    assert setup.model.p.reconfig_type == 2
    assert setup.invariants == (
        "LeaderHasAllAckedValues",
        "NoLogDivergence",
        "MaxOneReconfigurationAtATime",
    )
    assert setup.symmetry
