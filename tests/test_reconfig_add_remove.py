"""RaftWithReconfigAddRemove differential tests: TPU kernels vs the
independent oracle (standard-raft/RaftWithReconfigAddRemove.tla, 1,083
lines), wide-message bag round-trips, BFS count parity, and the
documented missing-MaxClusterSize cfg diagnosis."""

import numpy as np
import pytest

from pathlib import Path

import jax

from raft_tpu.checker.bfs import BFSChecker
from raft_tpu.models.reconfig_raft import (
    ReconfigRaftModel,
    ReconfigRaftParams,
    cached_model,
)
from raft_tpu.oracle.reconfig_oracle import (
    LEADER,
    NOTMEMBER,
    ReconfigRaftOracle,
    most_recent_reconfig_entry,
)

from conftest import collect_states as _collect_states


def oracle_for(p: ReconfigRaftParams) -> ReconfigRaftOracle:
    return ReconfigRaftOracle(
        p.n_servers, p.n_values, p.init_cluster_size, p.max_elections,
        p.max_restarts, p.max_values_per_term, p.max_add_reconfigs,
        p.max_remove_reconfigs, p.min_cluster_size, p.max_cluster_size,
        include_thesis_bug=p.include_thesis_bug,
    )


# smaller than the reference cfg (3 servers not 4) to keep kernels quick;
# a 4-server case mirrors the reference constants
PARAMS = [
    ReconfigRaftParams(
        n_servers=3, n_values=1, init_cluster_size=2, max_elections=1,
        max_restarts=0, max_values_per_term=1, max_add_reconfigs=1,
        max_remove_reconfigs=1, min_cluster_size=2, max_cluster_size=3,
        msg_slots=64,
    ),
    ReconfigRaftParams(
        n_servers=4, n_values=1, init_cluster_size=3, max_elections=1,
        max_restarts=0, max_values_per_term=1, max_add_reconfigs=1,
        max_remove_reconfigs=1, min_cluster_size=2, max_cluster_size=4,
        msg_slots=72,
    ),
]


@pytest.mark.slow
@pytest.mark.parametrize("params", PARAMS)
def test_successor_sets_match_oracle(params):
    model = cached_model(params)
    oracle = oracle_for(params)
    states = _collect_states(oracle, max_depth=8, cap=110)
    vecs = np.stack([model.encode(st) for st in states])
    succs, valid, rank, ovf = jax.device_get(model.expand(vecs))
    assert not np.any(valid & ovf)
    for b, st in enumerate(states):
        got = sorted(
            oracle.serialize_full(model.decode(succs[b, a]))
            for a in range(model.A)
            if valid[b, a]
        )
        want = sorted(oracle.serialize_full(s2) for _l, s2 in oracle.successors(st))
        assert got == want, f"successor mismatch at state {b}"


def test_encode_decode_roundtrip():
    params = PARAMS[0]
    model = cached_model(params)
    oracle = oracle_for(params)
    for st in _collect_states(oracle, max_depth=7, cap=100):
        assert model.decode(model.encode(st)) == st


@pytest.mark.slow
def test_bfs_counts_match_oracle():
    params = PARAMS[0]
    model = cached_model(params)
    oracle = oracle_for(params)
    invs = (
        "LeaderHasAllAckedValues",
        "NoLogDivergence",
        "MaxOneReconfigurationAtATime",
    )
    checker = BFSChecker(model, invariants=invs, symmetry=True, chunk=256)
    res = checker.run(max_depth=7)
    ores = oracle.bfs(invariants=invs, symmetry=True, max_depth=7)
    assert res.violation is None and ores["violation"] is None
    assert res.distinct == ores["distinct"]
    assert res.depth_counts == ores["depth_counts"]
    assert res.total == ores["total"]


def test_reconfig_flow_add_then_snapshot():
    """Protocol sanity: the initial leader adds a server, which triggers a
    snapshot catch-up (nextIndex sentinel path, :795-824,:862-921)."""
    params = PARAMS[0]
    oracle = oracle_for(params)
    st = oracle.init_state()

    def step(prefix):
        nonlocal st
        for label, s2 in oracle.successors(st):
            if label.startswith(prefix):
                st = s2
                return
        raise AssertionError(f"no successor matching {prefix!r}")

    # leader 0, members {0,1}; add server 2
    assert st["state"][0] == LEADER
    step("AppendAddServerCommandToLog(0,2)")
    assert st["config"][0][1] == frozenset({0, 1, 2})
    assert st["config"][0][2] is False  # uncommitted reconfig
    assert st["nextIndex"][0][2] == -1  # PendingSnapshotRequest
    step("SendSnapshot(0,2)")
    assert st["nextIndex"][0][2] == -2
    # the new server must fence its term (0 -> 1) before accepting
    step("UpdateTerm")
    step("HandleSnapshotRequest")
    assert len(st["log"][2]) == 2  # InitCluster + AddServer
    assert st["config"][2][1] == frozenset({0, 1, 2})
    step("HandleSnapshotResponse")
    assert st["nextIndex"][0][2] == 3
    assert st["matchIndex"][0][2] == 2
    # replication to member 1, then commit of the config entry
    step("AppendEntries(0,1)")
    step("AcceptAppendEntriesRequest")
    step("HandleAppendEntriesResponse")
    step("AdvanceCommitIndex(0)")
    assert st["commitIndex"][0] == 2
    assert st["config"][0][2] is True  # reconfig committed
    assert oracle.max_one_reconfiguration_at_a_time(st)
    assert oracle.no_log_divergence(st)


def test_remove_leader_leaves_cluster():
    """A leader that commits its own removal becomes NotMember
    (:633-640); its commitIndex resets."""
    params = ReconfigRaftParams(
        n_servers=3, n_values=1, init_cluster_size=3, max_elections=1,
        max_restarts=0, max_values_per_term=1, max_add_reconfigs=0,
        max_remove_reconfigs=1, min_cluster_size=2, max_cluster_size=3,
        msg_slots=64,
    )
    oracle = oracle_for(params)
    st = oracle.init_state()

    def step(prefix):
        nonlocal st
        for label, s2 in oracle.successors(st):
            if label.startswith(prefix):
                st = s2
                return
        raise AssertionError(f"no successor matching {prefix!r}")

    step("AppendRemoveServerCommandToLog(0,0)")  # leader removes itself
    assert st["config"][0][1] == frozenset({1, 2})
    for peer in (1, 2):
        step(f"AppendEntries(0,{peer})")
        step("AcceptAppendEntriesRequest")
        step("HandleAppendEntriesResponse")
    step("AdvanceCommitIndex(0)")
    assert st["state"][0] == NOTMEMBER
    assert st["commitIndex"][0] == 0


def test_most_recent_reconfig_entry():
    log = (
        ("InitClusterCommand", 1, (1, frozenset({0, 1}))),
        ("AppendCommand", 1, 0),
        ("AddServerCommand", 1, (2, 2, frozenset({0, 1, 2}))),
    )
    idx, entry = most_recent_reconfig_entry(log)
    assert idx == 3 and entry[0] == "AddServerCommand"


@pytest.mark.skipif(
    not Path("/root/reference").exists(),
    reason="reference TLA+ spec tree not checked out at /root/reference",
)
def test_reference_cfg_diagnoses_missing_max_cluster_size():
    from raft_tpu.utils.cfg import CfgError, parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    path = (
        "/root/reference/specifications/standard-raft/"
        "RaftWithReconfigAddRemove.cfg"
    )
    cfg = parse_cfg(path)  # parses cleanly; the bug is builder-level
    with pytest.raises(CfgError, match="MaxClusterSize"):
        build_from_cfg(cfg, msg_slots=16)
    cfg = parse_cfg(path, lenient=True)
    setup = build_from_cfg(cfg, msg_slots=16)
    assert any("MaxClusterSize" in d for d in cfg.diagnostics)
    assert setup.model.name == "RaftWithReconfigAddRemove"
    assert setup.model.p.n_servers == 4
    assert setup.model.p.max_cluster_size == 4  # repaired to |Server|
    assert setup.model.p.init_cluster_size == 3
    assert not setup.model.p.include_thesis_bug
    assert setup.invariants == (
        "LeaderHasAllAckedValues",
        "NoLogDivergence",
        "MaxOneReconfigurationAtATime",
    )
    assert setup.symmetry
