"""Kernel contract auditor (``raft_tpu lint``): tier-1 coverage.

Four layers, cheapest first:

  1. unit fixtures per pass — the parsers and AST scanners each get a
     positive (violation flagged, right line) and a negative (clean /
     blessed source stays clean) fixture, no jax work involved;
  2. the seeded-mutation kit — every ``--mutate`` name must make its
     targeted pass fire (exit 3) with a ``file:line``-anchored error
     naming that pass: the negative controls proving the auditor is
     alive, not vacuously clean;
  3. the CLI surface — exit-code contract (0 / 3 / 64), ``--json``
     round-trip, ``--list``, and the ``python -m raft_tpu lint``
     dispatch;
  4. the full-registry smoke: every pass over every family on CPU,
     strict-clean, under the 60 s budget.

The events-drift regression for the ``stall`` contract-doc gap found
(and fixed) on this tree is pinned explicitly in
``test_schema_doc_mentions_every_declared_event``.
"""

import ast
import json
import re
import subprocess
import sys
import textwrap
import time

import pytest

from raft_tpu.analysis import events_drift, lanes, sync
from raft_tpu.analysis.cli import (
    PASSES, exit_code, lint_main, run_lint, verdict,
)
from raft_tpu.analysis.donation import parse_main_aliasing, tensor_bytes
from raft_tpu.analysis.findings import Finding, PassResult, rel
from raft_tpu.analysis.selftest import MUTATIONS, PASS_OF

# ------------------------------------------------------ findings model


def test_finding_paths_are_repo_relative():
    f = Finding("donation", "error", rel(__file__), 12, "msg")
    assert not f.path.startswith("/")
    assert f.location == f"{f.path}:12"
    d = f.to_dict()
    assert (d["pass"], d["severity"], d["line"]) == ("donation", "error", 12)


def test_severity_gating():
    def res(sev):
        return [PassResult("p", [Finding("p", sev, "x.py", 1, "m")], 1)]

    assert exit_code(res("error"), strict=False) == 3
    assert exit_code(res("error"), strict=True) == 3
    assert exit_code(res("warning"), strict=False) == 0
    assert exit_code(res("warning"), strict=True) == 3
    assert exit_code(res("info"), strict=True) == 0
    assert exit_code([PassResult("p", [], 1)], strict=True) == 0
    assert verdict(res("warning"), strict=True)["clean"] is False


# -------------------------------------------------- donation: parsing

ALIASED_HLO = (
    "module @jit_wave {\n"
    "  func.func public @main(%arg0: tensor<8x4xi32>, "
    "%arg1: tensor<8x4xi32> {tf.aliasing_output = 0 : i32}, "
    '%arg2: tensor<16xi64> {mhlo.layout_mode = "default", '
    "tf.aliasing_output = 1 : i32}) -> "
    '(tensor<8x4xi32> {jax.result_info = "[0]"}, tensor<16xi64>) {\n'
    "    return\n  }\n}\n"
)


def test_parse_main_aliasing_fixture():
    args, results = parse_main_aliasing(ALIASED_HLO)
    assert args == {
        0: ("8x4xi32", None), 1: ("8x4xi32", 0), 2: ("16xi64", 1),
    }
    assert results == ["8x4xi32", "16xi64"]


def test_tensor_bytes():
    assert tensor_bytes("8x4xi32") == 8 * 4 * 4
    assert tensor_bytes("16xi64") == 16 * 8
    assert tensor_bytes("i1") == 1  # scalar


# --------------------------------------------- hidden-sync: scan_source

SYNC_BAD = textwrap.dedent("""
    def run(self):
        while frontier_count:
            stats = jax.device_get(state)
            n = total.item()
            arr = np.asarray(make_batch())
""")

SYNC_CLEAN = textwrap.dedent("""
    def run(self):
        while frontier_count:
            # lint: sync-ok(once-per-wave snapshot)
            stats = jax.device_get(state)
            host = np.asarray(already_host_array)
        final = jax.device_get(state)
""")


def test_sync_scan_flags_loop_syncs():
    findings = []
    audited = sync.scan_source(SYNC_BAD, "fixture.py", ("run",), findings)
    assert audited == 1
    kinds = sorted(f.detail["call"] for f in findings)
    assert kinds == [".item()", "jax.device_get", "np.asarray(<call>)"]
    assert all(f.severity == "error" and f.line > 1 for f in findings)


def test_sync_scan_blessed_and_off_loop_clean():
    findings = []
    audited = sync.scan_source(SYNC_CLEAN, "fixture.py", ("run",), findings)
    assert audited == 1
    # blessed loop sync, plain-array asarray, and the post-loop
    # device_get are all fine
    assert findings == []


def test_sync_scan_only_hot_functions():
    findings = []
    audited = sync.scan_source(
        SYNC_BAD, "fixture.py", ("other_fn",), findings)
    assert audited == 0 and findings == []


# ---------------------------------------- lane-discipline: AST readers

RANKS_SRC = textwrap.dedent("""
    (R_A, R_B, R_C, R_D, R_E, R_F, R_G, R_H, R_I, R_J) = range(10)
    R_TIMEOUT, R_FSYNC = 10, 11
    SMALL, ENUM = 0, 1
""")


def test_module_max_rank_reads_base_and_extension():
    assert lanes.module_max_rank(RANKS_SRC) == 11


def test_module_max_rank_none_without_table():
    assert lanes.module_max_rank("X = 3\n") is None
    # arity mismatch between targets and range() is a reader refusal
    bad = "(A, B, C, D, E, F, G, H, I, J, K) = range(10)\n"
    assert lanes.module_max_rank(bad) is None


CV_BAD = textwrap.dedent("""
    class M:
        def _restart(self, s, i):
            d = self._dec(s)
            return d + self.p.max_restarts

        def describe(self):
            return self.p.max_restarts
""")

CV_GOOD = textwrap.dedent("""
    class M:
        def _restart(self, s, i):
            d = self._dec(s)
            return d + self._cv(d, "max_restarts")
""")


def test_scan_dyn_consts_flags_raw_read_in_packed_scope():
    findings = []
    audited = lanes.scan_dyn_consts(
        CV_BAD, "fixture.py", {"max_restarts"}, findings)
    assert audited == 1  # describe() has no packed state: out of scope
    assert len(findings) == 1
    assert findings[0].detail == {
        "function": "_restart", "constant": "max_restarts"}


def test_scan_dyn_consts_cv_route_clean():
    findings = []
    audited = lanes.scan_dyn_consts(
        CV_GOOD, "fixture.py", {"max_restarts"}, findings)
    assert audited == 1 and findings == []


# ------------------------------------------------- events-drift: AST

VALIDATOR_SRC = textwrap.dedent("""
    def validate_event(etype, ev):
        if etype == "wave":
            pass
        elif etype in ("stall", "preempt"):
            pass

    def unrelated(etype):
        if etype == "not_scanned":
            pass
""")


def test_branch_literals_fixture():
    lits = events_drift.branch_literals(VALIDATOR_SRC)
    assert set(lits) == {"wave", "stall", "preempt"}
    assert all(line > 1 for line in lits.values())


def test_missing_doc_mentions_word_boundary():
    doc = "covers wave and shard_stall rows"
    missing = events_drift.missing_doc_mentions(
        doc, {"wave", "shard_stall", "stall"})
    # "shard_stall" must NOT mask the missing "stall" mention
    assert missing == ["stall"]


def test_schema_doc_mentions_every_declared_event():
    """Regression for the drift this pass caught on this tree: the
    check_metrics_schema.py contract doc omitted `stall`."""
    import os

    from raft_tpu.analysis.findings import REPO_ROOT
    from raft_tpu.obs.events import EVENT_KEYS

    with open(os.path.join(REPO_ROOT, events_drift.SCHEMA_SCRIPT)) as fh:
        doc = ast.get_docstring(ast.parse(fh.read())) or ""
    assert events_drift.missing_doc_mentions(doc, set(EVENT_KEYS)) == []


def test_events_drift_pass_clean():
    res = events_drift.run()
    assert res.checked > 0
    assert not res.findings, [f.render() for f in res.findings]


# -------------------------------------------------- seeded mutations


@pytest.mark.parametrize("name", sorted(MUTATIONS))
def test_mutation_fires(name):
    """Each seeded contract violation makes exactly its targeted pass
    report an error anchored at file:line — lint would exit 3."""
    target = PASS_OF[name]
    with MUTATIONS[name]() as kw:
        results = run_lint((target,), {target: kw})
    assert exit_code(results, strict=False) == 3
    errors = [f for r in results for f in r.findings
              if f.severity == "error"]
    assert errors, f"mutation {name} produced no error finding"
    for f in errors:
        assert f.pass_id == target
        assert re.fullmatch(r"[^:]+\.py:\d+", f.location), f.location
        assert f.line > 0


def test_mutations_are_hermetic():
    """After the context exits, the targeted passes are clean again —
    a mutation must not leak into the shipped tree's verdict."""
    for name in ("injected-sync", "raw-const-read"):
        target = PASS_OF[name]
        with MUTATIONS[name]():
            pass
        res = run_lint((target,))
        assert not any(r.findings for r in res), name


# ------------------------------------------------------- CLI surface


def test_cli_usage_errors_exit_64(capsys):
    assert lint_main(["--bogus"]) == 64
    assert lint_main(["--pass", "no-such-pass"]) == 64
    assert lint_main(["--mutate", "no-such-mutation"]) == 64
    # a mutation whose target was excluded by --pass is a usage error
    assert lint_main(
        ["--pass", "events-drift", "--mutate", "injected-sync"]) == 64
    assert "raft_tpu lint" in capsys.readouterr().err


def test_cli_list_names_every_pass(capsys):
    assert lint_main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in PASSES:
        assert name in out


def test_cli_json_verdict_round_trips(capsys):
    rc = lint_main(["--json", "--strict", "--pass", "events-drift"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["strict"] is True and doc["clean"] is True
    assert doc["errors"] == 0 and doc["warnings"] == 0
    assert [p["pass"] for p in doc["passes"]] == ["events-drift"]


def test_cli_mutate_exits_3(capsys):
    rc = lint_main(["--mutate", "raw-const-read"])
    out = capsys.readouterr().out
    assert rc == 3
    assert "lane-discipline" in out
    assert re.search(r"raft_tpu/models/\w+\.py:\d+", out)


def test_module_dispatch_runs_lint():
    out = subprocess.run(
        [sys.executable, "-m", "raft_tpu", "lint", "--strict", "--json",
         "--pass", "events-drift", "--pass", "hidden-sync"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout)
    assert doc["clean"] is True
    assert [p["pass"] for p in doc["passes"]] == [
        "events-drift", "hidden-sync"]


# ------------------------------------------------- full-registry smoke


def test_full_lint_strict_clean_under_budget():
    """The acceptance gate: every pass over the full registry on CPU is
    strict-clean in under 60 s — ``raft_tpu lint --strict`` exits 0 on
    the shipped tree."""
    t0 = time.time()
    results = run_lint()
    elapsed = time.time() - t0
    assert [r.pass_id for r in results] == list(PASSES)
    for r in results:
        assert r.checked > 0, f"{r.pass_id} audited nothing"
        assert not r.findings, [f.render() for f in r.findings]
    assert exit_code(results, strict=True) == 0
    assert elapsed < 60, f"lint smoke took {elapsed:.1f}s (budget 60s)"
