"""Primitive-cost microbenchmark on the live backend (round 5).

Times the building blocks the chunk program and canonicalizer are made
of, so rewrites target the ops that actually serialize on this TPU:
  - elementwise mix throughput (the VPU roofline reference)
  - per-element dynamic gather (take_along_axis with [B, K] indices)
  - one-hot select-sum equivalent of the same gather (the candidate fix)
  - row gather (one index per row)
  - scatter (row + element)
  - 2-key u32 sort at chunk and frontier sizes
  - dynamic_update_slice (the candidate scatter replacement)
  - searchsorted probe
  - while_loop per-iteration overhead (the wave-fusion floor)
  - null dispatch (the tunnel floor)

Usage: python scripts/prim_micro.py [reps]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

REPS = int(sys.argv[1]) if len(sys.argv) > 1 else 5


def _sync(out):
    """block_until_ready does not actually wait on the axon tunnel
    backend (measured 0.03 ms for programs that cost >100 ms through
    profile.py's device_get path) — force a real sync by fetching one
    element of every output leaf."""
    for leaf in jax.tree_util.tree_leaves(out):
        np.asarray(jax.device_get(leaf.ravel()[:1] if leaf.ndim else leaf))


def timeit(name, fn, *args):
    _sync(fn(*args))  # compile
    ts = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        _sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    med = sorted(ts)[len(ts) // 2]
    print(f"{name:48s} {med*1e3:10.2f} ms  (min {min(ts)*1e3:.2f})")
    return med


def main():
    print("devices:", jax.devices())
    key = jax.random.PRNGKey(0)

    # --- null dispatch (tunnel floor) ---
    one = jnp.zeros((8,), jnp.int32)
    timeit("null dispatch", jax.jit(lambda x: x + 1), one)

    # --- calibration: 64 chained 4096^3 bf16 matmuls (~8.8 TFLOP) ---
    a = jax.random.normal(key, (4096, 4096), jnp.bfloat16)

    @jax.jit
    def mm64(m):
        def body(i, x):
            return x @ a
        return lax.fori_loop(0, 64, body, m)

    timeit("64 x 4096^3 bf16 matmul (8.8 TFLOP)", mm64, a)

    # --- elementwise throughput: 10M u32 lanes x 12 mix ops ---
    x32 = jax.random.randint(key, (10_000_000,), 0, 1 << 30, jnp.int32).astype(jnp.uint32)

    @jax.jit
    def mixchain(x):
        for _ in range(4):
            x = (x ^ (x >> np.uint32(16))) * np.uint32(0x85EBCA6B)
            x = (x ^ (x >> np.uint32(13))) * np.uint32(0xC2B2AE35)
            x = x ^ (x >> np.uint32(16))
        return x.sum()

    timeit("elementwise 10M lanes x 12 mix ops", mixchain, x32)

    # --- per-element dynamic gather: [B, VL] indices into [B, VL] ---
    B, VL = 32768, 334
    view = jax.random.randint(key, (B, VL), 0, 100, jnp.int32)
    idx = jax.random.randint(key, (B, VL), 0, VL, jnp.int32)
    timeit(
        f"take_along_axis [B={B}, VL={VL}] per-elem idx",
        jax.jit(lambda v, i: jnp.take_along_axis(v, i, axis=1).sum()),
        view, idx,
    )

    # --- same but small-idx one-hot select over S=5 server blocks ---
    S = 5
    inv = jax.random.randint(key, (B, S), 0, S, jnp.int32)
    blk = view[:, : S * 66].reshape(B, S, 66)

    @jax.jit
    def onehot_perm(b, i):
        out = jnp.zeros_like(b)
        for s in range(S):
            out = out + jnp.where((i[:, :, None] == s), b[:, s : s + 1, :], 0)
        return out.sum()

    timeit(f"one-hot block perm [B={B}, S=5, rest=66]", onehot_perm, blk, inv)

    # --- take_along_axis with [B, S] idx (tiny K) ---
    timeit(
        f"take_along_axis [B={B}, S=5] idx",
        jax.jit(lambda v, i: jnp.take_along_axis(v[:, :S], i, axis=1).sum()),
        view, inv,
    )

    # --- row gather: VC rows of W words by one index per row ---
    VC, W, CA = 65536, 144, 217088
    flat = jax.random.randint(key, (CA + 1, W), 0, 100, jnp.int32)
    sel = jax.random.randint(key, (VC,), 0, CA, jnp.int32)
    timeit(f"row gather [{VC} rows x {W}w from {CA}]",
           jax.jit(lambda f, s: f[s].sum()), flat, sel)

    # --- row scatter: VC rows into FCAP+1 buffer ---
    FCAP = 1 << 20
    buf = jnp.zeros((FCAP + 1, W), jnp.int32)
    rows = jax.random.randint(key, (VC, W), 0, 100, jnp.int32)
    dst = jax.random.randint(key, (VC,), 0, FCAP, jnp.int32)
    timeit(f"row scatter [{VC} rows x {W}w into {FCAP}]",
           jax.jit(lambda b, r, d: b.at[d].set(r)), buf, rows, dst)

    # --- element scatter: CA element writes (the sel construction) ---
    vals = jnp.arange(CA, dtype=jnp.int32)
    edst = jax.random.randint(key, (CA,), 0, VC, jnp.int32)
    ebuf = jnp.zeros((VC + 1,), jnp.int32)
    timeit(f"elem scatter [{CA} writes into {VC}]",
           jax.jit(lambda b, d, v: b.at[d].set(v)), ebuf, edst, vals)

    # --- contiguous write: dynamic_update_slice VC rows into FCAP ---
    timeit(
        f"dynamic_update_slice [{VC} rows x {W}w]",
        jax.jit(lambda b, r, c: lax.dynamic_update_slice(b, r, (c, 0))),
        buf, rows, jnp.int32(1000),
    )

    # --- 2-key u32 sorts ---
    from raft_tpu.ops.hashing import sort_u64, sort_u64_with_idx

    fps_vc = jax.random.randint(key, (VC,), 0, 1 << 30, jnp.int32).astype(jnp.uint64)
    fps_1m = jax.random.randint(key, (FCAP + VC,), 0, 1 << 30, jnp.int32).astype(jnp.uint64)
    timeit(f"sort_u64 [{VC}]", jax.jit(sort_u64), fps_vc)
    timeit(f"sort_u64_with_idx [{VC}]",
           jax.jit(lambda x: sort_u64_with_idx(x)[0]), fps_vc)
    timeit(f"sort_u64 [{FCAP + VC}] (wave merge)", jax.jit(sort_u64), fps_1m)

    # --- searchsorted probe: VC vals into 8M run ---
    run = jnp.sort(jax.random.randint(key, (1 << 23,), 0, 1 << 62, jnp.int64).astype(jnp.uint64))
    timeit(
        f"searchsorted probe [{VC} into 8M]",
        jax.jit(lambda r, v: jnp.searchsorted(r, v).sum()), run, fps_vc,
    )

    # --- while_loop per-iteration overhead: 256 trivial iterations ---
    @jax.jit
    def wloop(x):
        def body(c):
            i, a = c
            return i + 1, a + i
        _, a = lax.while_loop(lambda c: c[0] < 256, body, (jnp.int32(0), x))
        return a

    timeit("while_loop 256 trivial iters", wloop, jnp.int32(0))

    # --- while_loop with a real body: 16 iters of sort VC ---
    @jax.jit
    def wloop_sort(fps):
        def body(c):
            i, a = c
            return i + 1, sort_u64(a ^ jnp.uint64(1))
        _, a = lax.while_loop(lambda c: c[0] < 16, body, (jnp.int32(0), fps))
        return a

    timeit("while_loop 16 x sort_u64[VC] iters", wloop_sort, fps_vc)

    # --- DISPATCH PIPELINING: 16 chained separate jit calls, one sync ---
    step = jax.jit(lambda x: sort_u64(x ^ jnp.uint64(1)))

    def chained16(fps):
        for _ in range(16):
            fps = step(fps)
        return fps

    timeit("16 chained DISPATCHES of sort_u64[VC]", chained16, fps_vc)

    # --- static-table permutation gather under vmap (masked_min path) ---
    VL5 = 330
    view5 = jax.random.randint(key, (32768, VL5), 0, 100, jnp.int32)
    gidx120 = jnp.asarray(
        np.stack([np.random.permutation(VL5) for _ in range(120)]).astype(np.int32)
    )

    @jax.jit
    def vmap_perm_gather(v, g):
        h = jax.vmap(lambda gi: v[:, gi].sum(dtype=jnp.int32))(g)
        return h

    timeit("vmap 120-perm gather [32768 x 330]", vmap_perm_gather, view5, gidx120)
    timeit("vmap 12-perm gather [32768 x 330]",
           vmap_perm_gather, view5, gidx120[:12])

    # --- same via UNROLLED static numpy indexing (12 perms) ---
    gidx_np = np.asarray(gidx120)

    @jax.jit
    def unrolled_perm(v):
        h = jnp.int32(0)
        for t in range(12):
            h = h + v[:, gidx_np[t]].sum(dtype=jnp.int32)
        return h

    timeit("unrolled 12 static-perm gathers [32768 x 330]", unrolled_perm, view5)

    # --- one-hot matmul permutation of per-server blocks ---
    S5 = 5
    blk5 = view5[:, : S5 * 66].reshape(32768, S5, 66)
    oh = jax.nn.one_hot(inv, S5, dtype=jnp.int32)  # wrong inv shape ok for timing

    @jax.jit
    def mm_perm(b, o):
        return jnp.einsum("bts,bsk->btk", o, b).sum(dtype=jnp.int32)

    timeit("one-hot matmul block perm [32768, 5, 66]", mm_perm, blk5, oh)


if __name__ == "__main__":
    main()
