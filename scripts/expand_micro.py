"""Microbench: what does ONE chunk's successor expansion cost, by path?

Reproduces the "expand wall" numbers behind the guard-first sparse
expansion (models/base.py SparseExpandMixin): the dense path runs every
per-action kernel over all chunk*A candidate lanes and gathers the
VC-compacted survivors, while the guard-first path runs the DCE-derived
guard pass (valid/rank/ovf only, no W-wide rows) over the same grid and
then constructs successors just for the enabled worklist, vmapped per
action group over a static budget plan. Both paths produce bit-identical
[VC, W] compacted blocks.

Two dense baselines are timed, because they differ enormously:

  dense_mat  vmap of the full kernels MATERIALIZING the [chunk, A, W]
             successor tensor (what any consumer that keeps raw succs
             pays, and what the legacy engines paid while bag_put
             carried a lax.sort — sorts block producer fusion);
  dense      the same kernels jitted TOGETHER with the compaction
             gather. With the branchless shift-insert bag_put (ops/
             bag.py) every kernel is elementwise, so XLA fuses the
             producer into the gather and computes kernels only for
             gathered rows — the compiler discovers the guard-first
             schedule implicitly. Fusion is a backend heuristic with no
             contract (it vanished with one lax.sort in the kernel);
             the explicit guard-first path makes the sparse schedule a
             guarantee, bounds worst-case work by the audited budget
             plan (overflow aborts instead of silently densifying), and
             exports enabled_density / expand_budget_ovf gauges.

``speedup_mat`` is guard-first vs dense_mat (the lane-ratio claim);
``speedup`` is vs the fused dense baseline — on backends whose fusion
already sparsifies the gather it hovers near or below 1x, which is the
honest cost of the explicit worklist bookkeeping. The grid sweeps the
apply budget (``--vpg``, per-state units; ``loose`` keeps the
overflow-impossible bound) against chunk size on a REAL reachable
frontier (guard density is whatever the model exhibits there — the
``density`` column reports it).

Defaults mirror the raft3 PROFILE workload geometry (3 servers, 2
values, msg_slots=32 -> A=56); ``--vpg tuned`` is that workload's
measured per-group budget dict, ``--vpg 8`` a flat per-group cap of 8
per state, ``loose`` the overflow-impossible bound (all chunk*A lanes,
grouped — isolates the grouping overhead with zero lane savings).

Usage:
  python scripts/expand_micro.py [--chunk 1024 4096]
                                 [--vpg loose 8 tuned]
                                 [--servers 3] [--values 2]
                                 [--elections 3] [--restarts 1]
                                 [--msg-slots 32] [--depth 10]
                                 [--reps 5] [--platform cpu]

Writes EXPAND_MICRO.json at the repo root (device provenance + one row
per (chunk, vpg) cell). scripts/profile_workloads.py --md-only folds the
summary into PROFILE.md.
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _time(fn, *args, reps=5):
    """Median wall seconds of fn(*args) with block_until_ready."""
    import jax

    jax.block_until_ready(fn(*args))  # warm / compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def bench_cell(model, batch_h, vpg, reps):
    import jax
    import jax.numpy as jnp
    import numpy as np

    C = len(batch_h)
    A, W = model.A, model.layout.W
    VC = min(C * A, C * 16)
    batch = jnp.asarray(batch_h)

    # -- dense path: full kernels over every lane + compaction gather
    def dense(b):
        succs, valid, rank, ovf = jax.vmap(model._expand1)(b)
        vflat = valid.reshape(-1)
        vpos = jnp.cumsum(vflat) - 1
        sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
        sel = (
            jnp.full((VC + 1,), C * A, jnp.int32)
            .at[sdst]
            .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
        )
        flatp = jnp.concatenate(
            [succs.reshape(C * A, W), jnp.zeros((1, W), jnp.int32)],
            axis=0,
        )
        return flatp[sel], sel < C * A

    # -- guard-first path, split so each phase gets its own row
    guards = jax.jit(lambda b: jax.vmap(model.guards1)(b))

    def worklist(valid):
        vflat = valid.reshape(-1)
        vpos = jnp.cumsum(vflat) - 1
        sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
        sel = (
            jnp.full((VC + 1,), C * A, jnp.int32)
            .at[sdst]
            .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
        )
        return sel, sel < C * A

    plan = model.sparse_plan(C, VC, vpg)
    apply_j = jax.jit(
        lambda b, s, sv: model.sparse_apply(b, s, sv, plan)
    )

    dense_j = jax.jit(dense)
    # full-kernel vmap that must materialize [C, A, W] — no gather for
    # the producer to fuse into (valid/rank fold into the same fusion,
    # so succs-only is the honest materialized cost)
    dense_mat_j = jax.jit(lambda b: jax.vmap(model._expand1)(b)[0])
    wl_j = jax.jit(worklist)
    valid, _, _ = guards(batch)
    sel, selv = wl_j(valid)
    flatc_d, _ = dense_j(batch)
    flatc_s, ovf = apply_j(batch, sel, selv)
    parity = bool(
        np.array_equal(np.asarray(flatc_d), np.asarray(flatc_s))
    )
    density = float(jnp.sum(valid)) / (C * A)

    row = {
        "chunk": C, "A": A, "W": W, "vc": VC,
        "vpg": "loose" if vpg is None else vpg,
        "plan_lanes": int(sum(plan)),
        "dense_lanes": C * A,
        "density": round(density, 4),
        "budget_ovf": bool(ovf),
        "parity": parity,
        "dense_ms": round(_time(dense_j, batch, reps=reps) * 1e3, 3),
        "dense_mat_ms": round(
            _time(dense_mat_j, batch, reps=reps) * 1e3, 3),
        "guards_ms": round(_time(guards, batch, reps=reps) * 1e3, 3),
        "apply_ms": round(
            _time(apply_j, batch, sel, selv, reps=reps) * 1e3, 3),
    }
    sparse_ms = max(row["guards_ms"] + row["apply_ms"], 1e-6)
    row["speedup"] = round(row["dense_ms"] / sparse_ms, 2)
    row["speedup_mat"] = round(row["dense_mat_ms"] / sparse_ms, 2)
    return row


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--chunk", type=int, nargs="+", default=[1024, 4096])
    ap.add_argument("--vpg", nargs="+", default=["loose", "8", "tuned"])
    ap.add_argument("--servers", type=int, default=3)
    ap.add_argument("--values", type=int, default=2)
    ap.add_argument("--elections", type=int, default=3)
    ap.add_argument("--restarts", type=int, default=1)
    ap.add_argument("--msg-slots", type=int, default=32)
    ap.add_argument("--depth", type=int, default=10)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import numpy as np

    from raft_tpu.models.raft import RaftModel, RaftParams

    model = RaftModel(RaftParams(
        n_servers=args.servers, n_values=args.values,
        max_elections=args.elections, max_restarts=args.restarts,
        msg_slots=args.msg_slots,
    ))
    # a reachable frontier (manual wave loop with exact-bytes dedup):
    # guard density on real states is the honest input, random bit
    # patterns are not; shallow spaces tile the deepest wave
    frontier = model.init_states()
    seen = set()
    for _ in range(args.depth):
        nxt = []
        B, W = 1024, model.layout.W
        for off in range(0, len(frontier), B):
            cs = frontier[off:off + B]
            nb = len(cs)
            if nb < B:
                cs = np.concatenate(
                    [cs, np.repeat(cs[-1:], B - nb, axis=0)])
            succs, valid, _, _ = jax.device_get(model.expand(cs))
            valid = np.array(valid)
            valid[nb:] = False
            flat = np.array(succs).reshape(-1, W)
            for i in np.nonzero(valid.reshape(-1))[0]:
                t = flat[i].tobytes()
                if t not in seen:
                    seen.add(t)
                    nxt.append(flat[i])
        if not nxt:
            break
        frontier = np.array(nxt, dtype=np.int32)
        if len(frontier) >= max(args.chunk):
            break
    del seen

    rows = []
    hdr = (f"{'chunk':>6} {'vpg':>6} {'lanes':>8} {'dense':>10} "
           f"{'densemat':>10} {'guards':>10} {'apply':>10} "
           f"{'vs_fused':>8} {'vs_mat':>8} {'ovf':>5}")
    print(hdr)
    for C in args.chunk:
        reps_needed = -(-C // len(frontier))
        batch_h = np.tile(frontier, (reps_needed, 1))[:C]
        for v in args.vpg:
            # "tuned" = the raft3 PROFILE workload's measured per-group
            # budgets (scripts/profile_workloads.py carries the same
            # dict with the measurement provenance)
            if v == "loose":
                vpg = None
            elif v == "tuned":
                vpg = {
                    "Restart": 2.25, "RequestVote": 1.25,
                    "BecomeLeader": 0.1875, "ClientRequest": 1.0,
                    "AdvanceCommitIndex": 0.109375,
                    "AppendEntries": 0.953125, "HandleMessage": 5.75,
                }
            else:
                vpg = float(v)
            row = bench_cell(model, batch_h, vpg, args.reps)
            row["vpg"] = v  # the grid label, not the expanded dict
            rows.append(row)
            if not row["parity"] and not row["budget_ovf"]:
                raise AssertionError(
                    f"sparse/dense parity failed in-budget: {row}")
            print(f"{row['chunk']:>6} {str(row['vpg']):>6} "
                  f"{row['plan_lanes']:>8} {row['dense_ms']:>8.2f}ms "
                  f"{row['dense_mat_ms']:>8.2f}ms "
                  f"{row['guards_ms']:>8.2f}ms {row['apply_ms']:>8.2f}ms "
                  f"{row['speedup']:>7.2f}x {row['speedup_mat']:>7.2f}x "
                  f"{str(row['budget_ovf']):>5}",
                  flush=True)

    out = {
        "meta": {
            "device": str(jax.devices()[0]),
            "when": time.strftime("%Y-%m-%d %H:%M:%S"),
            "model": model.name,
            "params": {
                "n_servers": args.servers, "n_values": args.values,
                "max_elections": args.elections,
                "max_restarts": args.restarts,
                "msg_slots": args.msg_slots,
            },
            "frontier_depth": args.depth,
            "reps": args.reps,
            "note": "ms per chunk of successor expansion on a real "
                    "reachable frontier; dense_mat = full kernels "
                    "materializing [chunk, A, W] (no gather to fuse "
                    "into), dense = same kernels jitted with the "
                    "compaction gather (with the branchless bag_put the "
                    "backend fuses the producer into the gather — an "
                    "implicit, contract-free sparse schedule), "
                    "guard-first = DCE guard pass + per-group budgeted "
                    "apply over the enabled worklist (the explicit, "
                    "budget-audited schedule; bit-identical output, "
                    "parity checked per cell unless the budget "
                    "overflowed). speedup is vs dense, speedup_mat vs "
                    "dense_mat",
        },
        "rows": rows,
    }
    path = os.path.join(ROOT, "EXPAND_MICRO.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
