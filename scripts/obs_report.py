"""Offline telemetry digest: JSONL event stream -> Markdown report.

Renders a run recorded with ``--metrics-out`` (see raft_tpu/obs) into a
human-readable digest: manifest provenance, the summary block, the
TLC-style per-action coverage table, the frontier depth histogram, an
occupancy sparkline over waves, and any stall events.

Deliberately dependency-free (stdlib only — no jax, no numpy, no
raft_tpu import): the report renders on any machine the JSONL file is
copied to, including ones without the accelerator toolchain.

Usage:
    python scripts/obs_report.py run.jsonl [--all] [--out report.md]

By default only the LAST run in the file is reported (a stream may hold
several; each ``manifest`` event starts a new run); --all reports every
run in order.

Fleet streams (one multiplexed file from ``raft_tpu sweep
--metrics-out``) carry job-tagged runs — the queue arm's per-job runs
and the packed arm's synthesized per-job triples all land in the same
file with a ``job`` field on their events. When any are present, the
report opens with a fleet digest table (one row per job: exit cause,
distinct/total/depth/terminal, violation, seconds) built from every
job-tagged run in the file, and each per-run section is titled with its
job name.
"""

from __future__ import annotations

import argparse
import json
import sys

SPARK = "▁▂▃▄▅▆▇█"
BAR_WIDTH = 40


def sparkline(values) -> str:
    vals = [float(v) for v in values]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK[0] * len(vals)
    return "".join(
        SPARK[min(len(SPARK) - 1, int((v - lo) / (hi - lo) * len(SPARK)))]
        for v in vals
    )


def hbar(value: int, peak: int) -> str:
    if peak <= 0:
        return ""
    return "#" * max(1 if value else 0, round(value / peak * BAR_WIDTH))


def split_runs(lines) -> list[list[dict]]:
    """Group decoded events into runs; a manifest starts a new run."""
    runs: list[list[dict]] = []
    for raw in lines:
        raw = raw.strip()
        if not raw:
            continue
        try:
            ev = json.loads(raw)
        except ValueError:
            continue
        if not isinstance(ev, dict) or "event" not in ev:
            continue
        if ev["event"] == "manifest" or not runs:
            runs.append([])
        runs[-1].append(ev)
    return runs


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def render_run(events: list[dict]) -> str:
    man = next((e for e in events if e["event"] == "manifest"), {})
    summ = next((e for e in events if e["event"] == "summary"), None)
    waves = [e for e in events if e["event"] == "wave"]
    stalls = [e for e in events if e["event"] == "stall"]
    covs = [e for e in events if e["event"] == "coverage"]
    cov = covs[-1] if covs else None
    names = man.get("action_names") or []

    out = []
    title = man.get("model", "unknown model")
    if man.get("job"):
        title += f" — job {man['job']}"
    out.append(f"# Telemetry report: {title} ({man.get('engine', '?')})")
    out.append("")
    for k in ("ident", "platform", "device", "device_count", "chunk",
              "symmetry", "invariants", "when"):
        if k in man:
            out.append(f"- **{k}**: {_fmt(man[k])}")
    out.append("")

    out.append("## Summary")
    out.append("")
    if summ is None:
        out.append("_no summary event — the run did not finish cleanly_")
    else:
        for k in ("exit_cause", "violation", "distinct", "total", "depth",
                  "terminal", "seconds", "distinct_per_s", "exhausted",
                  "waves", "stalls", "canon_memo_hit_rate"):
            if k in summ:
                out.append(f"- **{k}**: {_fmt(summ[k])}")
    out.append("")

    out.append("## Action coverage")
    out.append("")
    if cov is None or not cov.get("actions"):
        out.append("_no coverage events in the stream_")
    else:
        acts = cov["actions"]
        out.append("| action | enabled | fired | new distinct |")
        out.append("|---|---:|---:|---:|")
        dead = []
        for r, row in enumerate(acts):
            name = names[r] if r < len(names) else f"action[{r}]"
            e, f, n = int(row[0]), int(row[1]), int(row[2])
            out.append(f"| {name} | {e} | {f} | {n} |")
            if f == 0:
                dead.append(name)
        out.append("")
        out.append(
            f"{cov.get('actions_fired', 0)}/{cov.get('actions_total', 0)} "
            f"actions fired"
            + (f"; canon memo fill {cov['canon_memo_fill']}"
               if cov.get("canon_memo_fill") is not None else "")
        )
        for name in dead:
            out.append(f"- **WARNING**: action {name} never fired")
    out.append("")

    out.append("## Depth histogram")
    out.append("")
    hist = (cov or {}).get("frontier_hist") or []
    if not hist:
        out.append("_no frontier histogram recorded_")
    else:
        peak = max(int(x) for x in hist)
        out.append("```")
        for d, x in enumerate(hist):
            out.append(f"depth {d:>3}  {int(x):>10}  {hbar(int(x), peak)}")
        out.append("```")
    out.append("")

    out.append("## Wave profile")
    out.append("")
    if not waves:
        out.append("_no wave events in the stream_")
    else:
        out.append(f"- new distinct/wave:  `{sparkline([w['new'] for w in waves])}`")
        out.append(f"- wave seconds:       `{sparkline([w['wave_s'] for w in waves])}`")
        out.append(
            f"- seen-lane occupancy: `{sparkline([w['lsm_lanes'] for w in waves])}`"
            f" (last: {waves[-1]['lsm_lanes']} lanes in "
            f"{waves[-1]['lsm_runs']} runs)"
        )
        if cov is not None and cov.get("seen_lanes"):
            out.append(
                f"- final seen runs: {cov.get('probe_runs')} "
                f"(lanes per run: {cov['seen_lanes']}; "
                f"real fingerprints: {cov.get('seen_real')})"
            )
    out.append("")

    out.append("## Stalls")
    out.append("")
    if not stalls:
        out.append("_none_")
    else:
        for s in stalls:
            out.append(
                f"- wave {s.get('wave')} (depth {s.get('depth')}): "
                f"{_fmt(s.get('wave_s'))}s vs median "
                f"{_fmt(s.get('median_wave_s'))}s "
                f"({_fmt(s.get('factor'))}x)"
            )
    out.append("")
    return "\n".join(out)


def render_fleet_digest(runs: list[list[dict]]) -> str | None:
    """One table row per job-tagged run in the stream; None when the
    stream carries no fleet (job-tagged) runs at all."""
    rows = []
    for events in runs:
        man = next((e for e in events if e["event"] == "manifest"), {})
        job = man.get("job")
        if not job:
            continue
        summ = next((e for e in events if e["event"] == "summary"), None)
        rows.append((job, summ or {}))
    if not rows:
        return None
    out = ["# Fleet digest", ""]
    out.append(f"{len(rows)} job run(s) in this stream.")
    out.append("")
    out.append(
        "| job | exit | distinct | total | depth | terminal "
        "| violation | seconds |"
    )
    out.append("|---|---|---:|---:|---:|---:|---|---:|")
    for job, s in rows:
        out.append(
            f"| {job} | {s.get('exit_cause', '?')} "
            f"| {s.get('distinct', '')} | {s.get('total', '')} "
            f"| {s.get('depth', '')} | {s.get('terminal', '')} "
            f"| {s.get('violation') or '-'} | {_fmt(s.get('seconds', ''))} |"
        )
    out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs_report",
        description="Render a telemetry JSONL stream as a Markdown digest.",
    )
    ap.add_argument("path", help="JSONL file written via --metrics-out")
    ap.add_argument("--all", action="store_true",
                    help="report every run in the file (default: last only)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the report here instead of stdout")
    args = ap.parse_args(argv)

    with open(args.path) as fh:
        runs = split_runs(fh)
    if not runs:
        print(f"error: no telemetry events in {args.path}", file=sys.stderr)
        return 1
    picked = runs if args.all else runs[-1:]
    sections = []
    digest = render_fleet_digest(runs)
    if digest is not None:
        sections.append(digest)
    sections.extend(render_run(r) for r in picked)
    text = "\n---\n\n".join(sections)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    else:
        try:
            print(text)
        except BrokenPipeError:  # | head — truncated output is the ask
            sys.stderr.close()
            return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
