"""Offline telemetry digest: JSONL event stream -> Markdown report.

Renders a run recorded with ``--metrics-out`` (see raft_tpu/obs) into a
human-readable digest: manifest provenance, the summary block, the
TLC-style per-action coverage table, the frontier depth histogram, an
occupancy sparkline over waves, and any stall events. Runs recorded
with ``--timeline`` additionally get the wave-timeline observatory
sections: a stage-share table aggregated over the sampled waves (the
live counterpart of PROFILE.md's offline per-stage isolation), an
analytic HBM watermark digest from the memwatch events, and — on
sharded runs — a per-shard critical-path table (work share, emigrant
lanes/bytes, shard seconds, skew) from the shard_wave events.

Deliberately dependency-free (stdlib only — no jax, no numpy, no
raft_tpu import): the report renders on any machine the JSONL file is
copied to, including ones without the accelerator toolchain.

Usage:
    python scripts/obs_report.py run.jsonl [--all] [--out report.md]

By default only the LAST run in the file is reported (a stream may hold
several; each ``manifest`` event starts a new run); --all reports every
run in order.

Fleet streams (one multiplexed file from ``raft_tpu sweep
--metrics-out``) carry job-tagged runs — the queue arm's per-job runs
and the packed arm's synthesized per-job triples all land in the same
file with a ``job`` field on their events. When any are present, the
report opens with a fleet digest table (one row per job: exit cause,
distinct/total/depth/terminal, violation, seconds) built from every
job-tagged run in the file, and each per-run section is titled with its
job name.
"""

from __future__ import annotations

import argparse
import json
import sys

SPARK = "▁▂▃▄▅▆▇█"
BAR_WIDTH = 40


def sparkline(values) -> str:
    vals = [float(v) for v in values]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK[0] * len(vals)
    return "".join(
        SPARK[min(len(SPARK) - 1, int((v - lo) / (hi - lo) * len(SPARK)))]
        for v in vals
    )


def hbar(value: int, peak: int) -> str:
    if peak <= 0:
        return ""
    return "#" * max(1 if value else 0, round(value / peak * BAR_WIDTH))


def split_runs(lines) -> list[list[dict]]:
    """Group decoded events into runs; a manifest starts a new run."""
    runs: list[list[dict]] = []
    for raw in lines:
        raw = raw.strip()
        if not raw:
            continue
        try:
            ev = json.loads(raw)
        except ValueError:
            continue
        if not isinstance(ev, dict) or "event" not in ev:
            continue
        if ev["event"] == "manifest" or not runs:
            runs.append([])
        runs[-1].append(ev)
    return runs


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def _fmt_bytes(n) -> str:
    n = int(n)
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def _render_timeline(out: list[str], events: list[dict], summ) -> None:
    """Stage-share table over the sampled --timeline waves. The live
    counterpart of PROFILE.md's offline stage profile: these shares come
    from real full-wave dispatches, not isolated micro-runs."""
    tls = [e for e in events if e["event"] == "timeline"]
    if not tls:
        return  # section omitted entirely on non-timeline runs
    out.append("## Wave timeline (sampled stage attribution)")
    out.append("")
    every = tls[0].get("every", "?")
    out.append(
        f"{len(tls)} sampled wave(s) at stride {every}: each sample ran "
        f"as separately timed stage dispatches (bit-identical to the "
        f"fused program). Shares are of summed stage seconds across samples — "
        f"compare with PROFILE.md's offline per-stage isolation."
    )
    out.append("")
    totals: dict[str, float] = {}
    for tl in tls:
        for stage, s in (tl.get("stages") or {}).items():
            totals[stage] = totals.get(stage, 0.0) + float(s)
    grand = sum(totals.values())
    out.append("| stage | seconds | share |")
    out.append("|---|---:|---:|")
    for stage, s in sorted(totals.items(), key=lambda kv: -kv[1]):
        share = s / grand if grand > 0 else 0.0
        out.append(f"| {stage} | {s:.4f} | {share:.0%} {hbar(round(share * 100), 100)} |")
    out.append("")
    ov = (summ or {}).get("timeline_overhead")
    if ov is not None:
        out.append(
            f"Sampling overhead: {ov:+.1%} per-wave amortized over the "
            f"every-{every} stride (sampled-vs-fused mean wave seconds)."
        )
        out.append("")


def _render_memory(out: list[str], events: list[dict]) -> None:
    """Analytic HBM watermark digest from the memwatch peak events."""
    mws = [e for e in events if e["event"] == "memwatch"]
    if not mws:
        return
    out.append("## Memory watermarks (analytic)")
    out.append("")
    last = mws[-1]
    out.append(
        f"- **peak live bytes**: {_fmt_bytes(last['peak_bytes'])} of "
        f"{_fmt_bytes(last['budget_bytes'])} budget "
        f"({float(last['frac']):.1%}), set at wave {last['wave']} "
        f"({len(mws)} peak event(s))"
    )
    out.append(f"- peak trajectory: `{sparkline([m['peak_bytes'] for m in mws])}`")
    breakdown = last.get("breakdown") or {}
    if breakdown:
        peak = max(int(v) for v in breakdown.values()) if breakdown else 0
        out.append("")
        out.append("| buffer family | bytes at peak |  |")
        out.append("|---|---:|---|")
        for fam, b in sorted(breakdown.items(), key=lambda kv: -int(kv[1])):
            out.append(f"| {fam} | {_fmt_bytes(b)} | {hbar(int(b), peak)} |")
    out.append("")


def _render_shards(out: list[str], events: list[dict], waves: list[dict]) -> None:
    """Per-shard critical-path table from the shard_wave events of a
    sharded --timeline run: who does the work, who emigrates states,
    and how skewed the mesh is."""
    sws = [e for e in events if e["event"] == "shard_wave"]
    if not sws:
        return
    dc = sws[0].get("device_count", 0)
    by_shard: dict[int, list[dict]] = {}
    for sw in sws:
        by_shard.setdefault(int(sw["shard"]), []).append(sw)
    out.append("## Shard critical path")
    out.append("")
    n_waves = len({sw["wave"] for sw in sws})
    out.append(
        f"{dc} shard(s) over {n_waves} sampled wave(s). `shard_s` is the "
        f"analytic per-shard compute attribution (lockstep SPMD: compute "
        f"seconds x work share x D); skew is max/median of summed shard_s."
    )
    out.append("")
    out.append(
        "| shard | new distinct | work share | emigrant lanes "
        "| emigrant bytes | shard_s | exchange_s |"
    )
    out.append("|---:|---:|---:|---:|---:|---:|---:|")
    sums = []
    for shard in sorted(by_shard):
        rows = by_shard[shard]
        new = sum(int(r["new"]) for r in rows)
        lanes = sum(int(r["routed_lanes"]) for r in rows)
        rbytes = sum(int(r["routed_bytes"]) for r in rows)
        ssec = sum(float(r["shard_s"]) for r in rows)
        exch = sum(float(r["exchange_s"]) for r in rows)
        share = (
            sum(float(r["work_share"]) for r in rows) / len(rows)
            if rows else 0.0
        )
        sums.append(ssec)
        out.append(
            f"| {shard} | {new} | {share:.1%} | {lanes} "
            f"| {_fmt_bytes(rbytes)} | {ssec:.4f} | {exch:.4f} |"
        )
    out.append("")
    if sums:
        srt = sorted(sums)
        mid = len(srt) // 2
        median = srt[mid] if len(srt) % 2 else (srt[mid - 1] + srt[mid]) / 2
        skew = (max(sums) / median) if median > 0 else 0.0
        out.append(f"- **shard skew** (max/median shard_s): {skew:.2f}x")
    shares = [
        w["exchange_share"] for w in waves
        if w.get("exchange_share") is not None
    ]
    if shares:
        out.append(
            f"- **exchange share** of sampled device seconds: mean "
            f"{sum(shares) / len(shares):.1%}, last {shares[-1]:.1%} "
            f"(`{sparkline(shares)}`)"
        )
    out.append("")


def render_run(events: list[dict]) -> str:
    man = next((e for e in events if e["event"] == "manifest"), {})
    summ = next((e for e in events if e["event"] == "summary"), None)
    waves = [e for e in events if e["event"] == "wave"]
    stalls = [e for e in events if e["event"] == "stall"]
    covs = [e for e in events if e["event"] == "coverage"]
    cov = covs[-1] if covs else None
    names = man.get("action_names") or []

    out = []
    title = man.get("model", "unknown model")
    if man.get("job"):
        title += f" — job {man['job']}"
    out.append(f"# Telemetry report: {title} ({man.get('engine', '?')})")
    out.append("")
    for k in ("ident", "platform", "device", "device_count", "chunk",
              "symmetry", "invariants", "when"):
        if k in man:
            out.append(f"- **{k}**: {_fmt(man[k])}")
    out.append("")

    out.append("## Summary")
    out.append("")
    if summ is None:
        out.append("_no summary event — the run did not finish cleanly_")
    else:
        for k in ("exit_cause", "violation", "distinct", "total", "depth",
                  "terminal", "seconds", "distinct_per_s", "exhausted",
                  "waves", "stalls", "canon_memo_hit_rate",
                  "timeline_every", "timeline_waves", "timeline_overhead",
                  "hbm_peak_bytes", "hbm_peak_frac"):
            if k in summ:
                out.append(f"- **{k}**: {_fmt(summ[k])}")
    out.append("")

    out.append("## Action coverage")
    out.append("")
    if cov is None or not cov.get("actions"):
        out.append("_no coverage events in the stream_")
    else:
        acts = cov["actions"]
        out.append("| action | enabled | fired | new distinct |")
        out.append("|---|---:|---:|---:|")
        dead = []
        for r, row in enumerate(acts):
            name = names[r] if r < len(names) else f"action[{r}]"
            e, f, n = int(row[0]), int(row[1]), int(row[2])
            out.append(f"| {name} | {e} | {f} | {n} |")
            if f == 0:
                dead.append(name)
        out.append("")
        out.append(
            f"{cov.get('actions_fired', 0)}/{cov.get('actions_total', 0)} "
            f"actions fired"
            + (f"; canon memo fill {cov['canon_memo_fill']}"
               if cov.get("canon_memo_fill") is not None else "")
        )
        for name in dead:
            out.append(f"- **WARNING**: action {name} never fired")
    out.append("")

    out.append("## Depth histogram")
    out.append("")
    hist = (cov or {}).get("frontier_hist") or []
    if not hist:
        out.append("_no frontier histogram recorded_")
    else:
        peak = max(int(x) for x in hist)
        out.append("```")
        for d, x in enumerate(hist):
            out.append(f"depth {d:>3}  {int(x):>10}  {hbar(int(x), peak)}")
        out.append("```")
    out.append("")

    out.append("## Wave profile")
    out.append("")
    if not waves:
        out.append("_no wave events in the stream_")
    else:
        out.append(f"- new distinct/wave:  `{sparkline([w['new'] for w in waves])}`")
        out.append(f"- wave seconds:       `{sparkline([w['wave_s'] for w in waves])}`")
        out.append(
            f"- seen-lane occupancy: `{sparkline([w['lsm_lanes'] for w in waves])}`"
            f" (last: {waves[-1]['lsm_lanes']} lanes in "
            f"{waves[-1]['lsm_runs']} runs)"
        )
        if cov is not None and cov.get("seen_lanes"):
            out.append(
                f"- final seen runs: {cov.get('probe_runs')} "
                f"(lanes per run: {cov['seen_lanes']}; "
                f"real fingerprints: {cov.get('seen_real')})"
            )
    out.append("")

    _render_timeline(out, events, summ)
    _render_memory(out, events)
    _render_shards(out, events, waves)

    out.append("## Stalls")
    out.append("")
    if not stalls:
        out.append("_none_")
    else:
        for s in stalls:
            out.append(
                f"- wave {s.get('wave')} (depth {s.get('depth')}): "
                f"{_fmt(s.get('wave_s'))}s vs median "
                f"{_fmt(s.get('median_wave_s'))}s "
                f"({_fmt(s.get('factor'))}x)"
            )
    out.append("")
    return "\n".join(out)


def render_fleet_digest(runs: list[list[dict]]) -> str | None:
    """One table row per job-tagged run in the stream; None when the
    stream carries no fleet (job-tagged) runs at all."""
    rows = []
    for events in runs:
        man = next((e for e in events if e["event"] == "manifest"), {})
        job = man.get("job")
        if not job:
            continue
        summ = next((e for e in events if e["event"] == "summary"), None)
        # per-job wall-clock: summed wave seconds of THIS job's run —
        # unlike summary `seconds` it stays comparable between the queue
        # arm (one process per job) and the packed arm (synthesized
        # per-job summaries share one device program)
        wall = sum(
            float(e.get("wave_s", 0) or 0)
            for e in events if e["event"] == "wave"
        )
        rows.append((job, summ or {}, wall))
    if not rows:
        return None
    out = ["# Fleet digest", ""]
    out.append(f"{len(rows)} job run(s) in this stream.")
    out.append("")
    out.append(
        "| job | exit | distinct | total | depth | terminal "
        "| violation | seconds | wall (waves) |"
    )
    out.append("|---|---|---:|---:|---:|---:|---|---:|---:|")
    for job, s, wall in rows:
        out.append(
            f"| {job} | {s.get('exit_cause', '?')} "
            f"| {s.get('distinct', '')} | {s.get('total', '')} "
            f"| {s.get('depth', '')} | {s.get('terminal', '')} "
            f"| {s.get('violation') or '-'} | {_fmt(s.get('seconds', ''))} "
            f"| {wall:.3f} |"
        )
    out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs_report",
        description="Render a telemetry JSONL stream as a Markdown digest.",
    )
    ap.add_argument("path", help="JSONL file written via --metrics-out")
    ap.add_argument("--all", action="store_true",
                    help="report every run in the file (default: last only)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the report here instead of stdout")
    args = ap.parse_args(argv)

    with open(args.path) as fh:
        runs = split_runs(fh)
    if not runs:
        print(f"error: no telemetry events in {args.path}", file=sys.stderr)
        return 1
    picked = runs if args.all else runs[-1:]
    sections = []
    digest = render_fleet_digest(runs)
    if digest is not None:
        sections.append(digest)
    sections.extend(render_run(r) for r in picked)
    text = "\n---\n\n".join(sections)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    else:
        try:
            print(text)
        except BrokenPipeError:  # | head — truncated output is the ask
            sys.stderr.close()
            return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
