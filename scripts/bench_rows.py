"""BASELINE.md rows 2-5 benchmarks (run on the real TPU chip).

Row 1 (exhaust standard-raft Raft.cfg) is the driver benchmark
(bench.py). This script measures the remaining rows and writes
BENCH_ROWS.json at the repo root:

  row 2  standard-raft deep BFS: 5 servers, MaxLogLen=5, MaxTerm=5,
         safety-only -> sustained distinct states/sec under a budget
         (the reference gives no numbers; TLC row is "likely
         intractable", BASELINE.md:28)
  row 3  raft-and-fsync RaftFsync.cfg -> parity-gated same-depth
         wall-clock ratio vs the in-repo Python oracle + deep run
  row 4  pull-raft PullRaft.cfg (lenient v2 repair) -> same protocol
  row 5  flexible-raft FlexibleRaft.cfg -> device simulation rate (the
         cfg's prescribed mode, FlexibleRaft.cfg:5) + a bounded-depth
         exhaustive sweep with symmetry (120 server permutations)

Every exhaustive row runs the two-chunk-geometry parity gate first
(checker/parity.py) so no number from a miscompiled batch geometry is
recorded. Protocol notes mirror bench.py: vs_oracle ratios are measured
on the identical same-depth workload, nulled when counts diverge.

Usage:  python scripts/bench_rows.py            (all rows)
        BENCH_ROWS_BUDGET_S=120 python scripts/bench_rows.py 3 4
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET = float(os.environ.get("BENCH_ROWS_BUDGET_S", "150"))
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "BENCH_ROWS.json")
REF = "/root/reference/specifications"


def manifest_fields(tel) -> dict:
    """Provenance subset of the telemetry manifest event, attached to
    every row so a BENCH_ROWS number carries the fingerprint-formula
    revision, memo geometry and device kind that produced it."""
    man = next((e for e in tel.events if e["event"] == "manifest"), {})
    return {k: man.get(k) for k in
            ("ident", "hashv", "canon_memo_cap", "device", "platform",
             "chunk")}


def coverage_fields(model, res) -> dict | None:
    """Action-coverage digest for a deep-run provenance block: actions
    fired / total and the least-covered action, so a throughput number
    also says how much of the spec's Next relation the run exercised."""
    from raft_tpu.obs import coverage_digest

    cov = getattr(res, "coverage", None)
    names = getattr(model, "ACTION_NAMES", None)
    if cov is None or not names:
        return None
    return coverage_digest(names, cov)


def gate(model, invs, depth, chunks=(1024, 2048), **caps):
    from raft_tpu.checker.parity import parity_gate

    g = parity_gate(model=model, invariants=invs, symmetry=True,
                    depth=depth, chunks=chunks, **caps)
    return g


def cmp_and_deep(model, invs, oracle, cmp_depth, chunk=2048,
                 frontier_cap=1 << 18, seen_cap=1 << 22, journal_cap=1 << 22):
    from raft_tpu.checker.device_bfs import DeviceBFS

    dev = DeviceBFS(model, invariants=invs, symmetry=True, chunk=chunk,
                    frontier_cap=frontier_cap, seen_cap=seen_cap,
                    journal_cap=journal_cap)
    dev.run(max_depth=1)  # compile outside the timed window (TLC-fair:
    # the oracle pays no compile either; the steady-state rate is what
    # the deep run measures)
    t0 = time.perf_counter()
    dres = dev.run(max_depth=cmp_depth)
    t_tpu = time.perf_counter() - t0
    t0 = time.perf_counter()
    ores = oracle.bfs(invariants=(), symmetry=True, max_depth=cmp_depth,
                      time_budget_s=6 * BUDGET)
    t_oracle = time.perf_counter() - t0
    match = (ores["distinct"] == dres.distinct
             and ores["depth_counts"] == dres.depth_counts)
    from raft_tpu.obs import Telemetry

    tel = Telemetry()
    deep = dev.run(time_budget_s=BUDGET, telemetry=tel)
    return {
        "manifest": manifest_fields(tel),
        "same_depth_cmp": {
            "depth": cmp_depth,
            "distinct": dres.distinct,
            "tpu_s": round(t_tpu, 2),
            "oracle_s": round(t_oracle, 2),
            "counts_match": match,
        },
        "vs_oracle_wallclock": (
            round(t_oracle / t_tpu, 2) if t_tpu > 0 and match else None
        ),
        "deep": {
            "distinct": deep.distinct,
            "depth": deep.depth,
            "exhausted": deep.exhausted,
            "terminal": deep.terminal,
            "seconds": round(deep.seconds, 2),
            "distinct_per_s": round(deep.states_per_sec, 1),
            "violation": deep.violation.invariant if deep.violation else None,
            "coverage": coverage_fields(model, deep),
        },
    }


def row2():
    """Deep-BFS stress: 5 servers / 5 values (MaxLogLen=5) / MaxTerm=5."""
    from raft_tpu.checker.device_bfs import DeviceBFS
    from raft_tpu.models.raft import RaftParams, cached_model

    p = RaftParams(n_servers=5, n_values=5, max_elections=4, max_restarts=0,
                   msg_slots=64)
    model = cached_model(p)
    invs = ("LeaderHasAllAckedValues", "NoLogDivergence")
    g = gate(model, invs, depth=4, chunks=(512, 1024),
             frontier_cap=1 << 14, seen_cap=1 << 18)
    out = {"workload": "Raft 5 servers / 5 values / MaxTerm 5, safety-only",
           "parity_gate": str(g)}
    if not g.ok:
        out["error"] = "parity gate failed"
        return out
    dev = DeviceBFS(model, invariants=invs, symmetry=True, chunk=2048,
                    frontier_cap=1 << 19, seen_cap=1 << 23,
                    journal_cap=1 << 23, max_frontier_cap=1 << 21,
                    max_seen_cap=1 << 25, max_journal_cap=1 << 25)
    dev.run(max_depth=1)  # compile outside the budgeted window (the v3
    # canonicalizer's three tiers push compile past 2 min on this chip)
    from raft_tpu.obs import Telemetry

    tel = Telemetry()
    deep = dev.run(time_budget_s=BUDGET, collect_metrics=True, telemetry=tel)
    last = deep.metrics[-1] if deep.metrics else {}
    out["manifest"] = manifest_fields(tel)
    # round 6 provenance: (a) the emit is the compact+cursor-append path
    # (scripts/emit_micro.py measures it against the retired scatter);
    # (b) the BENCH_r05 4.3x final-wave cliff at depth 32 was NOT emit
    # cost — the seen truncate-merge's `[:target]` left a non-ladder-size
    # run when target > concat, forcing a full wave-program retrace at a
    # never-precompiled shape on the next wave. The merge now pads its
    # output to exactly `target` with U64_MAX sentinels (invisible to
    # export/probe), so every wave re-enters a precompiled signature.
    out["notes"] = {
        "emit": "compact+cursor-append (round 6); per-wave emit_rows/"
                "frontier_fill gauges in the metrics stream",
        "expand": "guard-first sparse (round 7): DCE guard pass + "
                  "per-group budgeted apply, loose plan at default "
                  "knobs; per-wave enabled_density/expand_budget_ovf "
                  "gauges in the metrics stream; scripts/expand_micro."
                  "py prices it against both dense baselines "
                  "(materialized and gather-fused)",
        "final_wave_cliff": "BENCH_r05 depth-32 4.3x wave-time cliff "
                            "diagnosed as a seen-merge shape retrace "
                            "(truncated non-ladder run size), fixed by "
                            "padding merged seen runs to the ladder "
                            "target; wave times now stay on precompiled "
                            "signatures",
    }
    out["deep"] = {
        "distinct": deep.distinct,
        "depth": deep.depth,
        "exhausted": deep.exhausted,
        "seconds": round(deep.seconds, 2),
        "sustained_distinct_per_s": round(deep.states_per_sec, 1),
        "final_wave": last,
        "coverage": coverage_fields(model, deep),
    }
    return out


def row3():
    from raft_tpu.models.registry import build_from_cfg, oracle_for_setup
    from raft_tpu.utils.cfg import parse_cfg

    cfg = parse_cfg(f"{REF}/raft-and-fsync/RaftFsync.cfg")
    setup = build_from_cfg(cfg, msg_slots=40)
    g = gate(setup.model, setup.invariants, depth=8,
             frontier_cap=1 << 15, seen_cap=1 << 19)
    out = {"workload": "RaftFsync.cfg (3 servers, fsync policy F/T/T)",
           "parity_gate": str(g)}
    if not g.ok:
        out["error"] = "parity gate failed"
        return out
    # depth 15 (round 4): at depth 13 the whole device run is ~6 s of
    # mostly per-wave dispatch latency and the 1-core oracle arm's
    # wall-clock fluctuates 2x run-to-run, so the ratio was noise
    out.update(cmp_and_deep(setup.model, setup.invariants,
                            oracle_for_setup(setup), cmp_depth=15))
    return out


def row4():
    from raft_tpu.models.registry import build_from_cfg, oracle_for_setup
    from raft_tpu.utils.cfg import parse_cfg

    cfg = parse_cfg(f"{REF}/pull-raft/PullRaft.cfg", lenient=True)
    setup = build_from_cfg(cfg, msg_slots=40)
    g = gate(setup.model, setup.invariants, depth=8,
             frontier_cap=1 << 15, seen_cap=1 << 19)
    out = {"workload": "PullRaft.cfg (3 servers; lenient v2 repair)",
           "parity_gate": str(g)}
    if not g.ok:
        out["error"] = "parity gate failed"
        return out
    out.update(cmp_and_deep(setup.model, setup.invariants,
                            oracle_for_setup(setup), cmp_depth=15))
    return out


def row5():
    from raft_tpu.checker.device_bfs import DeviceBFS
    from raft_tpu.checker.simulate import Simulator
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.utils.cfg import parse_cfg

    cfg = parse_cfg(f"{REF}/flexible-raft/FlexibleRaft.cfg")
    setup = build_from_cfg(cfg, msg_slots=48)
    out = {"workload": "FlexibleRaft.cfg (5 servers, EQ=3/RQ=4; cfg "
                       "prescribes simulation)"}
    sim = Simulator(setup.model, invariants=setup.invariants, walks=256,
                    max_behavior_depth=40, seed=0)
    t0 = time.perf_counter()
    sres = sim.run(max_behaviors=1024)
    out["simulation"] = {
        "behaviors": sres.behaviors,
        "steps": sres.steps,
        "seconds": round(time.perf_counter() - t0, 2),
        "steps_per_s": round(sres.states_per_sec, 1),
        "violation": sres.violation.invariant if sres.violation else None,
    }
    # bounded-depth exhaustive sweep (symmetry = 120 permutations)
    dev = DeviceBFS(setup.model, invariants=setup.invariants, symmetry=True,
                    chunk=1024, frontier_cap=1 << 17, seen_cap=1 << 21,
                    journal_cap=1 << 21)
    dev.run(max_depth=1)  # compile outside the budgeted window
    from raft_tpu.obs import Telemetry

    tel = Telemetry()
    deep = dev.run(time_budget_s=BUDGET, telemetry=tel)
    out["manifest"] = manifest_fields(tel)
    out["bounded_bfs"] = {
        "distinct": deep.distinct,
        "depth": deep.depth,
        "exhausted": deep.exhausted,
        "seconds": round(deep.seconds, 2),
        "distinct_per_s": round(deep.states_per_sec, 1),
        "violation": deep.violation.invariant if deep.violation else None,
        "coverage": coverage_fields(setup.model, deep),
    }
    return out


def main():
    import jax

    rows = {"2": row2, "3": row3, "4": row4, "5": row5}
    pick = sys.argv[1:] or list(rows)
    results = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            results = json.load(f)
    results.setdefault("meta", {})
    results["meta"].update({
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "budget_s": BUDGET,
        "when": time.strftime("%Y-%m-%d %H:%M:%S"),
    })
    for r in pick:
        print(f"=== row {r} ===", flush=True)
        t0 = time.perf_counter()
        try:
            results[f"row{r}"] = rows[r]()
        except Exception as e:  # record the failure, keep going
            results[f"row{r}"] = {"error": f"{type(e).__name__}: {e}"}
        results[f"row{r}"]["row_wall_s"] = round(time.perf_counter() - t0, 1)
        results[f"row{r}"]["when"] = results["meta"]["when"]
        print(json.dumps({f"row{r}": results[f"row{r}"]}, indent=1), flush=True)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
