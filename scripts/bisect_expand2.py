"""Stage-4 bisect: characterize the batch-size-dependent vmap expansion
divergence on axon. Checks determinism, affected batch sizes, and the
specific (row, action, word) lanes that differ.
"""

import numpy as np
import jax

from raft_tpu.utils.cfg import parse_cfg
from raft_tpu.models.registry import build_from_cfg
from raft_tpu.ops.symmetry import Canonicalizer

DEPTH = 9

cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
setup = build_from_cfg(cfg, msg_slots=32)
model = setup.model
canon = Canonicalizer.for_model(model, symmetry=True)
W, A = model.layout.W, model.A

expand1 = jax.jit(jax.vmap(model._expand1))
init = model.init_states()
frontier = np.asarray(init)


def host_fps(states):
    return np.array(
        jax.device_get(canon.fingerprints(np.asarray(states))), dtype=np.uint64
    )


seen = set(host_fps(frontier).tolist())
for d in range(DEPTH):
    succs, valid, _r, _o = jax.device_get(expand1(frontier))
    flat = succs.reshape(-1, W)
    v = valid.reshape(-1)
    fps = host_fps(flat)
    nxt = []
    for i in np.nonzero(v)[0]:
        f = int(fps[i])
        if f not in seen:
            seen.add(f)
            nxt.append(flat[i])
    frontier = np.asarray(nxt)

F = len(frontier)
succs_s, valid_s, rank_s, _ = jax.device_get(expand1(frontier))

for B in (512, 1024, 2048, 4096, 8192):
    batch = np.zeros((B, W), np.int32)
    batch[:F] = frontier
    s1, v1, _, _ = jax.device_get(expand1(batch))
    s2, v2, _, _ = jax.device_get(expand1(batch))
    det = (np.asarray(s1) == np.asarray(s2)).all() and (
        np.asarray(v1) == np.asarray(v2)
    ).all()
    mm = int(((np.asarray(s1)[:F] != succs_s) & valid_s[:, :, None]).sum())
    vm = int((np.asarray(v1)[:F] != valid_s).sum())
    print(f"batch {B}: deterministic={bool(det)} succ-mismatch-words={mm} valid-mismatch={vm}")
    if mm and B == 4096:
        d = (np.asarray(s1)[:F] != succs_s) & valid_s[:, :, None]
        rows, acts, words = np.nonzero(d)
        print("  affected rows:", sorted(set(rows.tolist()))[:10])
        print("  affected actions:", sorted(set(acts.tolist())))
        print("  affected words:", sorted(set(words.tolist())))
        r, a = rows[0], acts[0]
        print("  example row", r, "action", a, model.action_label(int(rank_s[r, a]), int(a)) if hasattr(model, "action_label") else "")
        print("  batch-383 succ:", succs_s[r, a])
        print("  batch-4096 succ:", np.asarray(s1)[r, a])
        print("  input state:   ", frontier[r])
