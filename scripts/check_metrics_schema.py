#!/usr/bin/env python
"""Validate --metrics-out JSONL files against the declared event schema.

    python scripts/check_metrics_schema.py m.jsonl [more.jsonl ...]

Checks every line against raft_tpu.obs.events.DECLARED_EVENTS (the same
tuple the tier-1 smoke test pins): valid JSON per line, known event
type, every declared key present, wave indices strictly increasing
within a run, no wave after a run's summary, and a legal exit_cause on
each summary. A `stall` event (a wave exceeding the rolling-median
wave-time factor, obs/collector.py) and a `preempt` event (SIGTERM/
SIGINT observed, checkpoint path recorded) carry the generic known-
type + declared-keys checks. Coverage events get the structural checks on top: the
actions block must be [enabled, fired, new] non-negative int triples
matching actions_total, coverage must come before the run's summary
with non-decreasing wave indices, and the cumulative per-action
counters must be monotone non-decreasing cell by cell across the
stream. The resilience events (retry / resume / ckpt_generation /
preempt, from the self-healing runtime) are validated too: retry
attempts must be ints >= 1 strictly increasing across a supervised
session (a summary resets the counter), backoff_s non-negative,
resume/ckpt_generation generations ints >= 0, and ckpt_generation
skipped-diagnostics a list of strings. The elastic-mesh events ride the
same rules: a reshard (load-time fp-mod-D re-routing of a checkpoint
written on a different mesh size) must appear after the manifest but
before any wave and carry distinct from_d/to_d >= 1, while shard_lost /
shard_stall must name a shard index inside the mesh (0 <= shard <
device_count), carry a wave no older than the run's last completed
wave, and come before the summary. The wave-timeline observatory
events (--timeline runs) get structural rules too: a `timeline` event
must carry every >= 1, a stages dict whose keys are declared stage
names (expand / canon / dedup / emit / exchange / seen_merge /
checkpoint / host) with non-negative second values, and a wave_s >= 0;
a `memwatch` event (emitted only when the analytic live-byte watermark
sets a new peak) must keep peak_bytes monotone non-decreasing across
the run with total_bytes <= peak_bytes, non-negative byte counts
throughout, and a breakdown mapping buffer families to non-negative
byte counts; a `shard_wave` event (per-shard critical-path row on
sampled waves of a sharded run) must name a shard inside the mesh
(0 <= shard < device_count) with non-negative lanes / bytes / seconds
and a work_share in [0, 1]. Job-tagged streams (the one
multiplexed file a `raft_tpu sweep --metrics-out` run writes) get the
fleet rules: a `job` tag must be a non-empty string, each job's wave
indices must be strictly increasing within its run, and every job
manifest must be matched by exactly one summary with the same tag.
Exit status 0 iff every file is clean — bench.py runs this after each
telemetry-enabled run.

Dependency-free on purpose (no jax/numpy import happens): schema
validation must work on a machine with nothing but the repo checked
out, e.g. when auditing a metrics file copied off a TPU host.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from raft_tpu.obs.events import validate_lines  # noqa: E402


def validate_file(path: str) -> tuple[dict, list[str]]:
    """(event-type counts, problems) for one JSONL file."""
    with open(path) as fh:
        counts, problems = validate_lines(fh)
    if not counts:
        problems = [*problems, "no events at all (empty stream)"]
    elif "manifest" not in counts:
        problems = [*problems, "stream has no manifest event"]
    return counts, problems


def main(argv: list[str]) -> int:
    if not argv:
        print(__doc__.strip(), file=sys.stderr)
        return 64
    rc = 0
    for path in argv:
        try:
            counts, problems = validate_file(path)
        except OSError as e:
            print(f"{path}: cannot read ({e})", file=sys.stderr)
            rc = 1
            continue
        summary = " ".join(f"{k}={v}" for k, v in sorted(counts.items()))
        if problems:
            rc = 1
            print(f"{path}: INVALID ({summary})", file=sys.stderr)
            for p in problems:
                print(f"  {p}", file=sys.stderr)
        else:
            print(f"{path}: ok ({summary})")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
