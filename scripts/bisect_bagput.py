"""Stage-6 bisect: instrument the _append_entries -> bag_put pipeline and
find the first intermediate that differs between batch 383 and batch 4096
on axon."""

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.utils.cfg import parse_cfg
from raft_tpu.models.registry import build_from_cfg
from raft_tpu.ops.symmetry import Canonicalizer
from raft_tpu.ops.packing import EMPTY
from jax import lax

DEPTH = 9

cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
setup = build_from_cfg(cfg, msg_slots=32)
model = setup.model
canon = Canonicalizer.for_model(model, symmetry=True)
W, A = model.layout.W, model.A
p = model.p
S = p.n_servers
L = p.max_log

expand1 = jax.jit(jax.vmap(model._expand1))
init = model.init_states()
frontier = np.asarray(init)


def host_fps(states):
    return np.array(
        jax.device_get(canon.fingerprints(np.asarray(states))), dtype=np.uint64
    )


seen = set(host_fps(frontier).tolist())
for d in range(DEPTH):
    succs, valid, _r, _o = jax.device_get(expand1(frontier))
    flat = succs.reshape(-1, W)
    v = valid.reshape(-1)
    fps = host_fps(flat)
    nxt = []
    for i in np.nonzero(v)[0]:
        f = int(fps[i])
        if f not in seen:
            seen.add(f)
            nxt.append(flat[i])
    frontier = np.asarray(nxt)

F = len(frontier)
print(f"depth-{DEPTH} frontier: {F}")

pairs = [(i, j) for i in range(S) for j in range(S) if i != j]
ae_i = jnp.asarray([i for i, _ in pairs], jnp.int32)
ae_j = jnp.asarray([j for _, j in pairs], jnp.int32)


def ae_debug(s, i, j):
    """_append_entries with every bag_put intermediate returned."""
    d = model._dec(s)
    ni_ij = d["nextIndex"][i, j]
    prev_idx = ni_ij - 1
    lt_row = d["log_term"][i]
    lv_row = d["log_value"][i]
    prev_term = jnp.where(prev_idx > 0, lt_row[jnp.clip(prev_idx - 1, 0, L - 1)], 0)
    last_entry = jnp.minimum(d["log_len"][i], ni_ij)
    nent = (last_entry >= ni_ij).astype(jnp.int32)
    epos = jnp.clip(ni_ij - 1, 0, L - 1)
    eterm = jnp.where(nent > 0, lt_row[epos], 0)
    evalue = jnp.where(nent > 0, lv_row[epos], 0)
    khi, klo = model._pack(
        mtype=6,  # AEREQ value from raft.py
        mterm=d["currentTerm"][i],
        mprevLogIndex=prev_idx,
        mprevLogTerm=prev_term,
        nentries=nent,
        eterm=eterm,
        evalue=evalue,
        mcommitIndex=jnp.minimum(d["commitIndex"][i], last_entry),
        msource=i,
        mdest=j,
    )
    words = [d["msg_hi"], d["msg_lo"]]
    cnt = d["msg_cnt"]
    key = (khi, klo)
    eq = jnp.ones_like(words[0], dtype=bool)
    for w, k in zip(words, key):
        eq &= w == k
    existed = eq.any()
    cnt_inc = cnt + eq.astype(cnt.dtype)
    is_empty = words[0] == EMPTY
    slot = jnp.argmax(is_empty)
    ins = [w.at[slot].set(k) for w, k in zip(words, key)]
    cnt_ins = cnt.at[slot].set(jnp.int32(1))
    out = [jnp.where(existed, w, wi) for w, wi in zip(words, ins)]
    cnt2 = jnp.where(existed, cnt_inc, cnt_ins)
    sorted_ = lax.sort((*out, cnt2), num_keys=2)
    return dict(
        khi=khi, klo=klo, eq=eq, existed=existed, slot=slot,
        ins0=ins[0], ins1=ins[1], cnt_ins=cnt_ins,
        out0=out[0], out1=out[1], cnt2=cnt2,
        sh=sorted_[0], sl=sorted_[1], sc=sorted_[2],
    )


f = jax.jit(jax.vmap(jax.vmap(ae_debug, in_axes=(None, 0, 0)), in_axes=(0, None, None)))
o_small = {k: np.asarray(v) for k, v in jax.device_get(f(frontier, ae_i, ae_j)).items()}
batch = np.zeros((4096, W), np.int32)
batch[:F] = frontier
o_big = {k: np.asarray(v) for k, v in jax.device_get(f(batch, ae_i, ae_j)).items()}

for k in ["khi", "klo", "eq", "existed", "slot", "ins0", "ins1", "cnt_ins",
          "out0", "out1", "cnt2", "sh", "sl", "sc"]:
    a, b = o_small[k], o_big[k][:F]
    print(f"{k}: mismatches {int((a != b).sum())}")

# also check the sorted output against numpy lexsort of the device's own
# pre-sort arrays (batch 4096)
bad = 0
o0, o1, c2 = o_big["out0"], o_big["out1"], o_big["cnt2"]
sh, sl_, sc = o_big["sh"], o_big["sl"], o_big["sc"]
for b in range(o0.shape[0]):
    for k in range(o0.shape[1]):
        order = np.lexsort((o1[b, k], o0[b, k]))
        if not (
            np.array_equal(sh[b, k], o0[b, k][order])
            and np.array_equal(sl_[b, k], o1[b, k][order])
            and np.array_equal(sc[b, k], c2[b, k][order])
        ):
            bad += 1
print("fused sort rows wrong vs numpy-of-device-presort:", bad)
