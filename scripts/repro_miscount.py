"""Reproduce + bisect the chunk=4096 TPU dedup miscount (VERDICT r2 Weak #2).

Runs Raft.cfg BFS depth-by-depth on the requested platform and chunk size,
printing per-depth new-state counts. Known-good oracle counts through depth
11 are asserted when --check is passed.
"""

import argparse
import os
import sys

p = argparse.ArgumentParser()
p.add_argument("--platform", default="tpu", choices=["tpu", "cpu"])
p.add_argument("--chunk", type=int, default=4096)
p.add_argument("--depth", type=int, default=10)
p.add_argument("--check", action="store_true")
args = p.parse_args()

if args.platform == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
import jax

if args.platform == "cpu":
    jax.config.update("jax_platforms", "cpu")

from raft_tpu.utils.cfg import parse_cfg
from raft_tpu.models.registry import build_from_cfg
from raft_tpu.checker.device_bfs import DeviceBFS

cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
setup = build_from_cfg(cfg, msg_slots=32)
checker = DeviceBFS(
    setup.model,
    invariants=setup.invariants,
    symmetry=True,
    chunk=args.chunk,
    frontier_cap=1 << 17,
    seen_cap=1 << 21,
    journal_cap=1 << 21,
)
res = checker.run(max_depth=args.depth, verbose=True)
print("depth_counts:", res.depth_counts)

# Oracle ground truth (depths 0..11) for Raft.cfg constants, symmetry on.
ORACLE = [1, 2, 4, 10, 28, 68, 174, 406, 852, 1608, 736 + 1608 - 1608]
# the verdict only records depth-10 new = 736 and depth-11 = 1361
KNOWN = {10: 736, 11: 1361}
if args.check:
    bad = False
    for d, n in KNOWN.items():
        if d < len(res.depth_counts) and res.depth_counts[d] != n:
            print(f"MISMATCH depth {d}: got {res.depth_counts[d]}, want {n}")
            bad = True
    sys.exit(1 if bad else 0)
