"""Stage-5 bisect: which action kernel diverges between batch 383 and 4096
on axon, when jitted in isolation?"""

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.utils.cfg import parse_cfg
from raft_tpu.models.registry import build_from_cfg
from raft_tpu.ops.symmetry import Canonicalizer

DEPTH = 9

cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
setup = build_from_cfg(cfg, msg_slots=32)
model = setup.model
canon = Canonicalizer.for_model(model, symmetry=True)
W, A = model.layout.W, model.A
p = model.p
S = p.n_servers

expand1 = jax.jit(jax.vmap(model._expand1))
init = model.init_states()
frontier = np.asarray(init)


def host_fps(states):
    return np.array(
        jax.device_get(canon.fingerprints(np.asarray(states))), dtype=np.uint64
    )


seen = set(host_fps(frontier).tolist())
for d in range(DEPTH):
    succs, valid, _r, _o = jax.device_get(expand1(frontier))
    flat = succs.reshape(-1, W)
    v = valid.reshape(-1)
    fps = host_fps(flat)
    nxt = []
    for i in np.nonzero(v)[0]:
        f = int(fps[i])
        if f not in seen:
            seen.add(f)
            nxt.append(flat[i])
    frontier = np.asarray(nxt)

F = len(frontier)
print(f"depth-{DEPTH} frontier: {F}")

iota_s = jnp.arange(S, dtype=jnp.int32)
pairs = [(i, j) for i in range(S) for j in range(S) if i != j]
ae_i = jnp.asarray([i for i, _ in pairs], jnp.int32)
ae_j = jnp.asarray([j for _, j in pairs], jnp.int32)
M = p.msg_slots

fams = {
    "restart": lambda s: jax.vmap(lambda i: model._restart(s, i))(iota_s),
    "request_vote": lambda s: jax.vmap(lambda i: model._request_vote(s, i))(iota_s),
    "become_leader": lambda s: jax.vmap(lambda i: model._become_leader(s, i))(iota_s),
    "client_request": lambda s: jax.vmap(
        lambda i: model._client_request(s, i, jnp.int32(0))
    )(iota_s),
    "advance_commit": lambda s: jax.vmap(
        lambda i: model._advance_commit_index(s, i)
    )(iota_s),
    "append_entries": lambda s: jax.vmap(
        lambda i, j: model._append_entries(s, i, j)
    )(ae_i, ae_j),
    "handle_message": lambda s: jax.vmap(
        lambda m: model._handle_message(s, m)
    )(jnp.arange(M, dtype=jnp.int32)),
}

batch = np.zeros((4096, W), np.int32)
batch[:F] = frontier

for name, fam in fams.items():
    f = jax.jit(jax.vmap(fam))
    o_small = jax.device_get(f(frontier))
    o_big = jax.device_get(f(batch))
    diffs = []
    for k, (a, b) in enumerate(zip(o_small, o_big)):
        a, b = np.asarray(a), np.asarray(b)
        d = int((a != b[:F]).sum())
        diffs.append(d)
    print(f"{name}: per-output mismatches {diffs}")
