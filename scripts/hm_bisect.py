"""Bisect the _handle_message cost on the live backend (round 5).

Progressive prefixes of the kernel body, each double-vmapped like
production, timed with the pipelined device_get timer. Identifies which
region owns the ~115 ms/chunk net cost that neither op count, bag sorts,
nor [C, M, W] traffic explains.
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import bag
from raft_tpu.ops.packing import EMPTY


def _sync(out):
    leaves = jax.tree_util.tree_leaves(out)
    np.asarray(jax.device_get(leaves[0].ravel()[:1]))


def timeit(name, fn, *args):
    _sync(fn(*args))
    ts = []
    for _ in range(4):
        t0 = time.perf_counter()
        out = None
        for _ in range(4):
            out = fn(*args)
        _sync(out)
        ts.append((time.perf_counter() - t0) / 4)
    print(f"{name:40s} {sorted(ts)[2]*1e3:9.1f} ms")


def main():
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.models.raft import NIL, RVREQ, RVRESP, AEREQ, AERESP, FOLLOWER, CANDIDATE

    cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
    setup = build_from_cfg(cfg, msg_slots=32)
    model = setup.model
    p = model.p
    L = p.max_log
    C, W, M = 4096, model.layout.W, p.msg_slots
    batch = jnp.zeros((C, W), jnp.int32)
    marange = jnp.arange(M, dtype=jnp.int32)
    packer = model.packer

    def body(s, m, upto):
        d = model._dec(s)
        hi, lo, cnt = d["msg_hi"], d["msg_lo"], d["msg_cnt"]
        khi, klo, kcnt = hi[m], lo[m], cnt[m]
        occupied = khi != EMPTY
        u = partial(packer.unpack, khi, klo)
        mtype, mterm = u("mtype"), u("mterm")
        src, dst = u("msource"), u("mdest")
        ct_dst = d["currentTerm"][dst]
        st_dst = d["state"][dst]
        recv = occupied & (kcnt > 0)
        b_upd = occupied & (mterm > ct_dst)
        if upto == 1:  # decode + basic guards
            return (b_upd | recv).astype(jnp.int32)
        last_t = model._last_term(d, dst)
        ll_dst = d["log_len"][dst]
        rv_logok = (u("mlastLogTerm") > last_t) | (
            (u("mlastLogTerm") == last_t) & (u("mlastLogIndex") >= ll_dst)
        )
        grant = (
            (mterm == ct_dst) & rv_logok
            & ((d["votedFor"][dst] == NIL) | (d["votedFor"][dst] == src + 1))
        )
        b_rvreq = recv & (mtype == RVREQ) & (mterm <= ct_dst)
        b_rvresp = recv & (mtype == RVRESP) & (mterm == ct_dst)
        prev_idx = u("mprevLogIndex")
        prev_term = u("mprevLogTerm")
        nent = u("nentries")
        lt_row = d["log_term"][dst]
        lv_row = d["log_value"][dst]
        ae_logok = (prev_idx == 0) | (
            (prev_idx > 0) & (prev_idx <= ll_dst)
            & (prev_term == lt_row[jnp.clip(prev_idx - 1, 0, L - 1)])
        )
        b_reject = (
            recv & (mtype == AEREQ) & (mterm <= ct_dst)
            & ((mterm < ct_dst)
               | ((mterm == ct_dst) & (st_dst == FOLLOWER) & ~ae_logok))
        )
        b_accept = (
            recv & (mtype == AEREQ) & (mterm == ct_dst)
            & ((st_dst == FOLLOWER) | (st_dst == CANDIDATE)) & ae_logok
        )
        b_aeresp = recv & (mtype == AERESP) & (mterm == ct_dst)
        if upto == 2:  # + all branch guards
            return (b_rvreq | b_rvresp | b_reject | b_accept | b_aeresp | grant).astype(jnp.int32)
        can_append = (nent != 0) & (ll_dst == prev_idx)
        needs_trunc = ((nent != 0) & (ll_dst >= prev_idx + 1)) | (
            (nent == 0) & (ll_dst > prev_idx))
        appending = can_append | (needs_trunc & (nent != 0))
        new_ll = jnp.where(appending, prev_idx + 1,
                           jnp.where(needs_trunc, prev_idx, ll_dst))
        lanes = jnp.arange(L, dtype=jnp.int32)
        changes = appending | needs_trunc
        keep = lanes < prev_idx
        app_pos = jnp.clip(prev_idx, 0, L - 1)
        nlt = jnp.where(keep, lt_row, 0).at[app_pos].set(
            jnp.where(appending, u("eterm"), 0))
        nlv = jnp.where(keep, lv_row, 0).at[app_pos].set(
            jnp.where(appending, u("evalue"), 0))
        nlt = jnp.where(changes, nlt, lt_row)
        nlv = jnp.where(changes, nlv, lv_row)
        if upto == 3:  # + accept log surgery
            return nlt.sum() + nlv.sum() + new_ll
        rhi, rlo = model._pack(mtype=RVRESP, mterm=ct_dst,
                               mvoteGranted=grant.astype(jnp.int32),
                               msource=dst, mdest=src)
        rjhi, rjlo = model._pack(mtype=AERESP, mterm=ct_dst, msuccess=0,
                                 mmatchIndex=0, msource=dst, mdest=src)
        achi, aclo = model._pack(mtype=AERESP, mterm=ct_dst, msuccess=1,
                                 mmatchIndex=prev_idx + nent,
                                 msource=dst, mdest=src)
        vg = jnp.where(
            u("mvoteGranted") > 0,
            d["votesGranted"].at[dst].set(
                d["votesGranted"][dst] | (jnp.int32(1) << src)),
            d["votesGranted"])
        succm = u("msuccess") > 0
        mmatch = u("mmatchIndex")
        ni2 = jnp.where(
            succm, d["nextIndex"].at[dst, src].set(mmatch + 1),
            d["nextIndex"].at[dst, src].set(
                jnp.maximum(d["nextIndex"][dst, src] - 1, 1)))
        mi2 = jnp.where(succm, d["matchIndex"].at[dst, src].set(mmatch),
                        d["matchIndex"])
        if upto == 4:  # + packs, vg, ni/mi
            return (rhi + rjhi + achi + vg.sum() + ni2.sum() + mi2.sum())
        c2 = bag.bag_discard_at(cnt, m)
        resp_hi = jnp.where(b_rvreq, rhi, jnp.where(b_reject, rjhi, achi))
        resp_lo = jnp.where(b_rvreq, rlo, jnp.where(b_reject, rjlo, aclo))
        phi, plo, pcnt, ex, povf = bag.bag_put(hi, lo, c2, resp_hi, resp_lo)
        if upto == 5:  # + bag ops
            return phi.sum() + plo.sum() + pcnt.sum() + ex
        putb = b_rvreq | b_reject | b_accept
        dropb = b_rvresp | b_aeresp
        upd = dict(
            currentTerm=jnp.where(b_upd, d["currentTerm"].at[dst].set(mterm),
                                  d["currentTerm"]),
            state=jnp.where(b_upd | b_accept,
                            d["state"].at[dst].set(FOLLOWER), d["state"]),
            votedFor=jnp.where(
                b_upd, d["votedFor"].at[dst].set(NIL),
                jnp.where(b_rvreq & grant,
                          d["votedFor"].at[dst].set(src + 1), d["votedFor"])),
            votesGranted=jnp.where(b_rvresp, vg, d["votesGranted"]),
            commitIndex=jnp.where(
                b_accept, d["commitIndex"].at[dst].set(u("mcommitIndex")),
                d["commitIndex"]),
            log_term=jnp.where(b_accept, d["log_term"].at[dst].set(nlt),
                               d["log_term"]),
            log_value=jnp.where(b_accept, d["log_value"].at[dst].set(nlv),
                                d["log_value"]),
            log_len=jnp.where(b_accept, d["log_len"].at[dst].set(new_ll),
                              d["log_len"]),
            nextIndex=jnp.where(b_aeresp, ni2, d["nextIndex"]),
            matchIndex=jnp.where(b_aeresp, mi2, d["matchIndex"]),
            msg_hi=jnp.where(putb, phi, hi),
            msg_lo=jnp.where(putb, plo, lo),
            msg_cnt=jnp.where(putb, pcnt, jnp.where(dropb, c2, cnt)),
        )
        succ = model._asm(d, **upd)
        return succ.sum()

    for upto in (1, 2, 3, 4, 5, 6):
        fn = jax.jit(lambda b, upto=upto: jax.vmap(
            lambda s: jax.vmap(lambda m: body(s, m, upto))(marange))(b))
        timeit(f"upto={upto}", fn, batch)


if __name__ == "__main__":
    main()
