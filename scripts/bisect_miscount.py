"""Bisect the chunk=4096 TPU dedup miscount inside DeviceBFS._chunk_step.

Builds the (known-correct) depth-9 frontier of Raft.cfg with a host-numpy
BFS, then runs the depth-9 -> depth-10 expansion through the same staged
computation as _chunk_step on device, fetching each intermediate and
comparing with a numpy recomputation from the device's own upstream
outputs. The first diverging stage is the culprit.
"""

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.utils.cfg import parse_cfg
from raft_tpu.models.registry import build_from_cfg
from raft_tpu.ops.hashing import U64_MAX
from raft_tpu.ops.symmetry import Canonicalizer

DEPTH = 9
CHUNK = 4096

cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
setup = build_from_cfg(cfg, msg_slots=32)
model = setup.model
canon = Canonicalizer.for_model(model, symmetry=True)
W, A = model.layout.W, model.A

# ---- host BFS to depth 9 (numpy dedup; ground truth) ----
def host_fps(states):
    return np.asarray(jax.device_get(canon.fingerprints(np.asarray(states))), dtype=np.uint64)

expand1 = jax.jit(jax.vmap(model._expand1))
init = model.init_states()
frontier = np.asarray(init)
seen = set(host_fps(frontier).tolist())
for d in range(DEPTH):
    succs, valid, _r, _o = jax.device_get(expand1(frontier))
    flat = succs.reshape(-1, W)
    v = valid.reshape(-1)
    fps = host_fps(flat)
    nxt, nfp = [], []
    for i in np.nonzero(v)[0]:
        f = int(fps[i])
        if f not in seen:
            seen.add(f)
            nxt.append(flat[i])
            nfp.append(f)
    frontier = np.asarray(nxt)
    print(f"host depth {d+1}: new {len(frontier)}")

F = len(frontier)
print(f"depth-{DEPTH} frontier: {F} states, seen={len(seen)}")

# ---- device stage-by-stage at chunk=4096 geometry ----
C = CHUNK
VC = C * 16
SCAP = 1 << 21
batch = np.zeros((C, W), np.int32)
batch[:F] = frontier
live = np.arange(C) < F

seen_arr = np.full(SCAP, np.uint64(U64_MAX), dtype=np.uint64)
sl = np.sort(np.fromiter(seen, dtype=np.uint64))
seen_arr[: len(sl)] = sl
seen_arr.sort()

@jax.jit
def stage_all(batch, seen):
    succs, valid, _rank, _ovf = jax.vmap(model._expand1)(batch)
    valid = valid & jnp.asarray(live)[:, None]
    vflat = valid.reshape(-1)
    vpos = jnp.cumsum(vflat) - 1
    sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
    sel = (
        jnp.full((VC + 1,), C * A, jnp.int32)
        .at[sdst]
        .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
    )
    selv = sel < C * A
    flatp = jnp.concatenate(
        [succs.reshape(C * A, W), jnp.zeros((1, W), jnp.int32)], axis=0
    )
    flatc = flatp[sel]
    fps = canon._fingerprints(flatc)
    fps = jnp.where(selv, fps, U64_MAX)
    pos = jnp.clip(jnp.searchsorted(seen, fps), 0, seen.shape[0] - 1)
    in_seen = seen[pos] == fps
    fresh = ~in_seen & (fps != U64_MAX)
    order = jnp.argsort(fps, stable=True)
    rf = fps[order]
    first_s = jnp.ones((VC,), bool).at[1:].set(rf[1:] != rf[:-1])
    first = jnp.zeros((VC,), bool).at[order].set(first_s)
    new = fresh & first
    return valid, sel, flatc, fps, in_seen, order, rf, first, new

valid, sel, flatc, fps, in_seen, order, rf, first, new = (
    np.asarray(jax.device_get(x)) for x in stage_all(batch, seen_arr)
)

print("n_new (device):", int(new.sum()))

# numpy recomputation from the device's own flatc/fps
vflat = valid.reshape(-1)
np_sel_count = int(vflat.sum())
print("valid count:", np_sel_count)

# stage A: sel correctness (compaction)
sel_expected = np.full(VC, C * A, np.int64)
idxs = np.nonzero(vflat)[0]
sel_expected[: len(idxs)] = idxs
badA = (sel.astype(np.int64) != sel_expected).sum()
print("stage A (compaction sel) mismatches:", badA)

# stage B: fingerprints — recompute on device in a separate small program
fps2 = np.array(
    jax.device_get(canon.fingerprints(np.asarray(flatc))), dtype=np.uint64
)
selv = sel < C * A
fps2[~selv] = np.uint64(U64_MAX)
badB = (fps != fps2).sum()
print("stage B (fingerprints in fused vs standalone) mismatches:", badB)

# stage C: in_seen probe
np_in_seen = np.isin(fps, sl)
badC = (in_seen != np_in_seen).sum()
print("stage C (seen probe) mismatches:", badC)

# stage D: argsort/first-occurrence
np_order = np.argsort(fps, kind="stable")
np_rf = fps[np_order]
sorted_ok = bool(np.all(rf[1:] >= rf[:-1]))
print("stage D rf sorted:", sorted_ok, "| rf == np_rf:", bool(np.all(rf == np_rf)))
np_first_s = np.ones(VC, bool)
np_first_s[1:] = np_rf[1:] != np_rf[:-1]
np_first = np.zeros(VC, bool)
np_first[np_order] = np_first_s
badD = (first != np_first).sum()
print("stage D (first-occurrence) mismatches:", badD)

# stage E: final new mask
np_new = ~np_in_seen & (fps != np.uint64(U64_MAX)) & np_first
badE = (new != np_new).sum()
print("stage E (new mask) mismatches:", badE, "| numpy n_new:", int(np_new.sum()))
