"""Sharded-checker scaling rows -> BENCH_ROWS.json["row_sharded"].

Round-4 verdict Next #3: real multi-chip hardware is not available in
this environment, so the scaling evidence runs on a VIRTUAL CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8, one physical core —
wall-clock therefore does NOT scale with D; what these rows prove is the
MACHINERY: exact count parity at every mesh size, all-to-all volume,
shard balance, and route_cap/growth behavior at >=100k-state frontiers).

  a) MaxElections=1 Raft workload (the driver dryrun's 6,247-state
     space) exhausted at D = 1/2/4/8, counts vs the single-device anchor
  b) depth-capped reference Raft.cfg at D = 8 driven into a WIDE wave
     (final frontier >= 100k states) — route_cap, capacity growth and
     balance hold far past the toy scale of the in-repo parity tests

Usage: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python scripts/bench_sharded.py
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

OUT = os.path.join(ROOT, "BENCH_ROWS.json")


def small_workload():
    from raft_tpu.models.raft import RaftParams, cached_model

    # the dryrun_multichip workload: 3 servers, MaxElections=1
    p = RaftParams(n_servers=3, n_values=1, max_elections=1, max_restarts=1,
                   msg_slots=24)
    return cached_model(p), ("LeaderHasAllAckedValues", "NoLogDivergence")


def main():
    from raft_tpu.checker.device_bfs import DeviceBFS
    from raft_tpu.parallel.sharded import ShardedBFS

    model, invs = small_workload()
    out = {"mesh": "virtual CPU devices (1 physical core; machinery "
                   "evidence, not wall-clock scaling)"}

    # single-device anchor counts
    anchor = DeviceBFS(model, invariants=invs, symmetry=True, chunk=512,
                       frontier_cap=1 << 13, seen_cap=1 << 15).run()
    out["anchor"] = {"distinct": anchor.distinct, "depth": anchor.depth,
                     "exhausted": anchor.exhausted}

    scaling = []
    for d in (1, 2, 4, 8):
        eng = ShardedBFS(model, invariants=invs, symmetry=True,
                         devices=jax.devices()[:d], chunk=256,
                         frontier_cap=1 << 12, seen_cap=1 << 14)
        t0 = time.perf_counter()
        res = eng.run(collect_metrics=True)
        dt = time.perf_counter() - t0
        assert res.distinct == anchor.distinct, (d, res.distinct)
        assert res.depth == anchor.depth
        last = res.metrics[-1] if res.metrics else {}
        scaling.append({
            "devices": d,
            "distinct": res.distinct,
            "depth": res.depth,
            "exhausted": res.exhausted,
            "seconds": round(dt, 2),
            "distinct_per_s": round(res.states_per_sec, 1),
            "a2a_bytes_total": sum(m.get("a2a_bytes", 0) for m in res.metrics),
            "final_shard_balance": last.get("shard_new"),
        })
        print(f"D={d}: {res.distinct} distinct, depth {res.depth}, "
              f"{dt:.1f}s, counts==anchor OK", flush=True)
    out["scaling_maxelections1"] = scaling

    # wide-wave evidence: reference Raft.cfg on a mesh, driven until a
    # frontier exceeds 100k states (route_cap/growth far past toy scale)
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.utils.cfg import parse_cfg

    cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
    setup = build_from_cfg(cfg, msg_slots=32)
    # D=4 / chunk=256: on the 1-core host the D per-device threads of
    # one program execution serialize, and XLA:CPU's collective
    # rendezvous kills the process if they drift >40 s apart — so the
    # per-program work (D * chunk expansions) must stay small even at
    # 100k-wide waves
    eng = ShardedBFS(setup.model, invariants=setup.invariants, symmetry=True,
                     devices=jax.devices()[:4], chunk=256,
                     frontier_cap=1 << 13, seen_cap=1 << 16,
                     max_frontier_cap=1 << 17, max_seen_cap=1 << 21,
                     max_journal_cap=1 << 21)
    t0 = time.perf_counter()
    res = eng.run(max_depth=22, collect_metrics=True)
    dt = time.perf_counter() - t0
    widest = max(m["frontier"] for m in res.metrics)
    # cross-check counts against the single-device engine at same depth
    ref = DeviceBFS(setup.model, invariants=setup.invariants, symmetry=True,
                    chunk=1024, frontier_cap=1 << 17, seen_cap=1 << 20,
                    max_seen_cap=1 << 22).run(max_depth=22)
    assert res.distinct == ref.distinct, (res.distinct, ref.distinct)
    assert list(res.depth_counts) == list(ref.depth_counts)
    out["wide_wave_raft_cfg"] = {
        "devices": 4,
        "max_depth": 22,
        "distinct": res.distinct,
        "widest_frontier": widest,
        "seconds": round(dt, 2),
        "a2a_bytes_total": sum(m.get("a2a_bytes", 0) for m in res.metrics),
        "final_shard_balance": res.metrics[-1].get("shard_new"),
        "counts_match_single_device": True,
    }
    print(f"wide wave: widest frontier {widest}, {res.distinct} distinct, "
          f"counts==single-device OK", flush=True)

    results = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            results = json.load(f)
    results["row_sharded"] = out
    with open(OUT, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
