#!/usr/bin/env python
"""Perf/correctness regression gate: metrics stream vs committed baseline.

    python scripts/bench_gate.py run.jsonl baseline.json [--json verdict.json]

Compares the LAST summary event of a --metrics-out JSONL stream against
a committed baseline file and exits 0 when every gated metric is inside
tolerance, 3 (the strict-gate exit code the CLI already uses for
coverage gates) when any metric is out, 64 on usage errors and 66 when
an input file is missing. A machine-readable verdict is always printed
on stdout as one JSON object; the failing metrics are also named on
stderr so CI logs show the reason without parsing JSON.

Baseline format (JSON)::

    {
      "note": "free-form provenance, ignored by the gate",
      "metrics": {
        "distinct":   {"value": 45,    "direction": "eq"},
        "seconds":    {"value": 12.0,  "rel_tol": 0.25, "direction": "max"},
        "depth":      {"value": 19,    "tol": 0,        "direction": "eq"}
      }
    }

Per-metric rules:

- ``direction: "eq"``  — |run - value| must be <= tolerance (default 0).
  Use for counts the checker must reproduce exactly (distinct, total,
  depth, terminal): a drift here is a correctness bug, not a perf one.
- ``direction: "max"`` — run must be <= value + tolerance. Use for
  costs (seconds, hbm_peak_bytes): bigger is worse.
- ``direction: "min"`` — run must be >= value - tolerance. Use for
  rates (distinct_per_s): smaller is worse.
- tolerance is ``tol`` (absolute) or ``rel_tol`` (fraction of the
  baseline value); giving both is a baseline error (exit 64).
- a gated metric missing from the run's summary, or null, fails the
  gate — silently skipping a metric would let a renamed field pass CI.

Dependency-free on purpose (stdlib only, no raft_tpu import): the gate
must run on a bare CI box or on a metrics file copied off a TPU host.
bench.py calls :func:`evaluate` directly to stamp gate verdicts into
its provenance block.
"""

from __future__ import annotations

import json
import sys

DIRECTIONS = ("eq", "max", "min")


def last_summary(lines) -> dict | None:
    """Decode a JSONL iterable and return the last summary event."""
    summ = None
    for raw in lines:
        raw = raw.strip()
        if not raw:
            continue
        try:
            ev = json.loads(raw)
        except ValueError:
            continue
        if isinstance(ev, dict) and ev.get("event") == "summary":
            summ = ev
    return summ


def evaluate(summary: dict, baseline: dict) -> dict:
    """Gate one summary event against a baseline dict.

    Returns the verdict object: ``{"pass": bool, "checked": N,
    "failures": [...], "metrics": {name: {...one row per gate...}}}``.
    Raises ValueError on a malformed baseline (unknown direction, both
    tol and rel_tol, non-dict metrics block) — the caller maps that to
    exit 64, distinct from a legitimate gate failure.
    """
    gates = baseline.get("metrics")
    if not isinstance(gates, dict) or not gates:
        raise ValueError("baseline has no metrics block")
    failures: list[str] = []
    rows: dict[str, dict] = {}
    for name, gate in sorted(gates.items()):
        if not isinstance(gate, dict) or "value" not in gate:
            raise ValueError(f"metric {name}: baseline entry needs a value")
        direction = gate.get("direction", "eq")
        if direction not in DIRECTIONS:
            raise ValueError(f"metric {name}: unknown direction {direction!r}")
        if "tol" in gate and "rel_tol" in gate:
            raise ValueError(f"metric {name}: give tol OR rel_tol, not both")
        want = float(gate["value"])
        tol = (
            float(gate["rel_tol"]) * abs(want)
            if "rel_tol" in gate else float(gate.get("tol", 0.0))
        )
        if tol < 0:
            raise ValueError(f"metric {name}: negative tolerance")
        got = summary.get(name)
        row = {"want": gate["value"], "tol": tol, "direction": direction,
               "got": got}
        if not isinstance(got, (int, float)) or isinstance(got, bool):
            row["ok"] = False
            reason = "missing from summary" if got is None else f"non-numeric ({got!r})"
            failures.append(f"{name}: {reason}")
        else:
            got = float(got)
            if direction == "eq":
                ok = abs(got - want) <= tol
                bound = f"|{got:g} - {want:g}| <= {tol:g}"
            elif direction == "max":
                ok = got <= want + tol
                bound = f"{got:g} <= {want:g} + {tol:g}"
            else:
                ok = got >= want - tol
                bound = f"{got:g} >= {want:g} - {tol:g}"
            row["ok"] = ok
            if not ok:
                failures.append(f"{name}: {bound} is false")
        rows[name] = row
    return {
        "pass": not failures,
        "checked": len(rows),
        "failures": failures,
        "metrics": rows,
    }


def main(argv: list[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="bench_gate",
        description="Gate a metrics JSONL stream against a committed baseline.",
    )
    ap.add_argument("metrics", help="JSONL file written via --metrics-out")
    ap.add_argument("baseline", help="baseline JSON with a metrics block")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="also write the verdict object to this path")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 64 if e.code not in (0, None) else 0

    try:
        with open(args.metrics) as fh:
            summ = last_summary(fh)
    except OSError as e:
        print(f"error: cannot read metrics: {e}", file=sys.stderr)
        return 66
    try:
        with open(args.baseline) as fh:
            baseline = json.load(fh)
    except OSError as e:
        print(f"error: cannot read baseline: {e}", file=sys.stderr)
        return 66
    except ValueError as e:
        print(f"error: baseline is not JSON: {e}", file=sys.stderr)
        return 64
    if summ is None:
        print("error: no summary event in metrics stream", file=sys.stderr)
        return 66

    try:
        verdict = evaluate(summ, baseline)
    except ValueError as e:
        print(f"error: bad baseline: {e}", file=sys.stderr)
        return 64
    verdict["metrics_file"] = args.metrics
    verdict["baseline_file"] = args.baseline
    text = json.dumps(verdict, indent=2, sort_keys=True)
    print(text)
    if args.json:
        with open(args.json, "w") as fh:
            fh.write(text + "\n")
    if verdict["pass"]:
        return 0
    for f in verdict["failures"]:
        print(f"GATE FAIL {f}", file=sys.stderr)
    return 3


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
