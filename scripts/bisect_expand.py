"""Stage-3 bisect: does the 4096-batch vmap expansion or the 65536-lane
compaction gather corrupt successor states on the TPU?

Compares, for the depth-9 Raft.cfg frontier (383 states):
  A. vmap(model._expand1) at batch 383 vs batch 4096 (rows 0..382)
  B. the fused compaction gather flatp[sel] vs a numpy gather of the same
     succs with the same sel
"""

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.utils.cfg import parse_cfg
from raft_tpu.models.registry import build_from_cfg
from raft_tpu.ops.symmetry import Canonicalizer

DEPTH = 9
C = 4096

cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
setup = build_from_cfg(cfg, msg_slots=32)
model = setup.model
canon = Canonicalizer.for_model(model, symmetry=True)
W, A = model.layout.W, model.A

expand1 = jax.jit(jax.vmap(model._expand1))
init = model.init_states()
frontier = np.asarray(init)


def host_fps(states):
    return np.array(
        jax.device_get(canon.fingerprints(np.asarray(states))), dtype=np.uint64
    )


seen = set(host_fps(frontier).tolist())
for d in range(DEPTH):
    succs, valid, _r, _o = jax.device_get(expand1(frontier))
    flat = succs.reshape(-1, W)
    v = valid.reshape(-1)
    fps = host_fps(flat)
    nxt = []
    for i in np.nonzero(v)[0]:
        f = int(fps[i])
        if f not in seen:
            seen.add(f)
            nxt.append(flat[i])
    frontier = np.asarray(nxt)

F = len(frontier)
print(f"depth-{DEPTH} frontier: {F}")

succs_s, valid_s, _r, _o = jax.device_get(expand1(frontier))  # batch 383

batch = np.zeros((C, W), np.int32)
batch[:F] = frontier
succs_b, valid_b, _r2, _o2 = jax.device_get(expand1(batch))  # batch 4096

dv = (valid_s != valid_b[:F]).sum()
print("A. valid mismatches (383 vs 4096 batch):", int(dv))
ds = (succs_s != succs_b[:F]).sum(), int(
    ((succs_s != succs_b[:F]) & valid_s[:, :, None]).sum()
)
print("A. succ word mismatches (all lanes, valid lanes):", ds)

# B. the compaction gather inside a jit at 65536 lanes
VC = C * 16
live = np.arange(C) < F


@jax.jit
def compact(batch):
    succs, valid, _rank, _ovf = jax.vmap(model._expand1)(batch)
    valid = valid & jnp.asarray(live)[:, None]
    vflat = valid.reshape(-1)
    vpos = jnp.cumsum(vflat) - 1
    sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
    sel = (
        jnp.full((VC + 1,), C * A, jnp.int32)
        .at[sdst]
        .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
    )
    flatp = jnp.concatenate(
        [succs.reshape(C * A, W), jnp.zeros((1, W), jnp.int32)], axis=0
    )
    return succs, sel, flatp[sel]


succs_f, sel, flatc = (np.asarray(jax.device_get(x)) for x in compact(batch))
print("B. fused succs == plain 4096-batch succs:",
      bool((succs_f == succs_b).all()))
flat_np = succs_f.reshape(C * A, W)
flatp_np = np.concatenate([flat_np, np.zeros((1, W), np.int32)], axis=0)
expect = flatp_np[sel]
bad = np.nonzero((flatc != expect).any(axis=1))[0]
print("B. gather mismatching lanes:", len(bad))
if len(bad):
    b = bad[0]
    print("lane", b, "sel", sel[b])
    print("device row:", flatc[b])
    print("expected  :", expect[b])
