"""Microbench: what does ONE chunk's frontier emit cost, by strategy?

Reproduces the "capacity-sized scatter penalty" claim that used to live
as a folklore number in device_bfs.py: scattering VC survivor rows into
a full-capacity [FCAP, W] buffer with arbitrary destination indices
(`.at[dst].set()`) versus the round-6 production path (dense-prefix
compaction to a [VC, W] block + ONE donated dynamic_update_slice at the
frontier cursor) versus a sort-based emit (stable argsort of the keep
mask + gather + the same cursor append).

All three variants write the same rows to the same destinations; all
donate the big buffer so XLA may update in place; the donated buffer is
rebuilt OUTSIDE the timed window each rep. The scatter's cost scales
with FCAP (the whole buffer is touched by the lowering), the appends'
with VC — sweeping FCAP at fixed VC is the point of the grid.

Usage:
  python scripts/emit_micro.py [--vc 32768 65536] [--fcap 262144 4194304]
                               [--w 64] [--reps 5] [--density 0.5]
                               [--platform cpu]

Writes EMIT_MICRO.json at the repo root (device provenance + one row per
(VC, FCAP) cell). scripts/profile_workloads.py --md-only folds the
summary into PROFILE.md.

W defaults to 64 (not a workload's real row width) to keep the 4M-row
cell around 1 GiB/buffer; pass --w to match a specific workload.
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _time_donated(fn, make_args, reps):
    """Median wall seconds of fn(*make_args()), args rebuilt outside the
    timed window each rep (donation consumes them)."""
    import jax

    ts = []
    for _ in range(reps):
        args = make_args()
        jax.block_until_ready(args)
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def bench_cell(vc, fcap, w, reps, density, rng):
    import jax
    import jax.numpy as jnp

    from raft_tpu.checker.util import dense_prefix_sel, emit_append

    new_h = rng.random(vc) < density
    n_new = int(new_h.sum())
    new = jnp.asarray(new_h)
    npos = jnp.asarray((new_h.cumsum() - 1).astype("int32"))
    flatc = jnp.asarray(rng.integers(1, 1 << 20, size=(vc, w), dtype="int64")
                        .astype("int32"))
    count = jnp.int32(0)

    # -- retired production emit: arbitrary-index scatter, drop row fcap
    def scatter_full(nb):
        dst = jnp.where(new, jnp.minimum(count + npos, fcap), fcap)
        return nb.at[dst].set(flatc)

    # -- round-6 production emit: compact to a dense [VC, W] block, one
    #    dynamic_update_slice at the cursor
    def compact_dus(nb):
        esel = dense_prefix_sel(new, npos, vc)
        blk = jnp.concatenate(
            [flatc, jnp.zeros((1, w), jnp.int32)], axis=0)[esel]
        nb, _ = emit_append(nb, blk, count, jnp.int32(n_new), fcap)
        return nb

    # -- alternative: stable sort of the keep mask compacts survivors to
    #    the front (argsort of ~new), then the same cursor append
    def sort_emit(nb):
        order = jnp.argsort(~new, stable=True)
        blk = flatc[order]
        nb, _ = emit_append(nb, blk, count, jnp.int32(n_new), fcap)
        return nb

    variants = {
        # scatter needs only the drop row past fcap; the appends need a
        # full VC-row drop region (same geometry the engines carry)
        "scatter_full": (scatter_full, fcap + 1),
        "compact_dus": (compact_dus, fcap + vc),
        "sort_emit": (sort_emit, fcap + vc),
    }
    row = {"vc": vc, "fcap": fcap, "n_new": n_new}
    for name, (fn, rows) in variants.items():
        jf = jax.jit(fn, donate_argnums=(0,))
        make = lambda rows=rows: (jnp.zeros((rows, w), jnp.int32),)
        jax.block_until_ready(jf(*make()))  # compile outside the timer
        row[f"{name}_ms"] = round(_time_donated(jf, make, reps) * 1e3, 3)
    row["scatter_over_compact"] = round(
        row["scatter_full_ms"] / max(row["compact_dus_ms"], 1e-6), 1)
    return row


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--vc", type=int, nargs="+", default=[32768, 65536])
    ap.add_argument("--fcap", type=int, nargs="+",
                    default=[262144, 4194304])
    ap.add_argument("--w", type=int, default=64)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--density", type=float, default=0.5)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import numpy as np

    rng = np.random.default_rng(0)
    rows = []
    hdr = (f"{'VC':>8} {'FCAP':>9} {'scatter':>10} {'compact':>10} "
           f"{'sort':>10} {'scatter/compact':>16}")
    print(hdr)
    for vc in args.vc:
        for fcap in args.fcap:
            row = bench_cell(vc, fcap, args.w, args.reps, args.density, rng)
            rows.append(row)
            print(f"{row['vc']:>8} {row['fcap']:>9} "
                  f"{row['scatter_full_ms']:>8.2f}ms "
                  f"{row['compact_dus_ms']:>8.2f}ms "
                  f"{row['sort_emit_ms']:>8.2f}ms "
                  f"{row['scatter_over_compact']:>15.1f}x", flush=True)

    out = {
        "meta": {
            "device": str(jax.devices()[0]),
            "when": time.strftime("%Y-%m-%d %H:%M:%S"),
            "w": args.w, "reps": args.reps, "density": args.density,
            "note": "ms per emit of one chunk's survivors into a "
                    "frontier-shaped [rows, W] i32 buffer; all variants "
                    "donate the buffer and rebuild it outside the timer",
        },
        "rows": rows,
    }
    path = os.path.join(ROOT, "EMIT_MICRO.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
