"""Stage-level profile of the three verdict workloads -> PROFILE.md.

Workloads (round-3 verdict Next #1):
  raft3   standard-raft Raft.cfg           (3 servers, 6 perms)
  fsync   raft-and-fsync RaftFsync.cfg     (3 servers, 6 perms)
  raft5   Raft 5s/5v/MaxTerm5 (BENCH row2) (5 servers, 120 perms)

Usage: python scripts/profile_workloads.py [raft3 fsync raft5] [--platform cpu]
Writes PROFILE.md + PROFILE.json at the repo root.

Without a /root/reference checkout, raft3 falls back to an equivalent
built-in 3-server geometry and fsync is skipped (its model is built
from the reference cfg only).
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
REF = "/root/reference/specifications"


def _model_raft3():
    if os.path.isdir(REF):
        from raft_tpu.models.registry import build_from_cfg
        from raft_tpu.utils.cfg import parse_cfg

        s = build_from_cfg(parse_cfg(f"{REF}/standard-raft/Raft.cfg"),
                           msg_slots=32)
        # reference-cfg geometry: keep the loose (overflow-impossible)
        # apply plan — the tuned budgets below were measured on the
        # built-in fallback's exact state space
        return s.model, s.invariants, dict(chunk=4096, frontier_cap=1 << 18,
                                           seen_cap=1 << 22, warm_depth=14)
    # no reference checkout: an equivalent built-in 3-server geometry
    # (same S/perm count — the knob the stage shares depend on)
    from raft_tpu.models.raft import RaftParams, cached_model

    p = RaftParams(n_servers=3, n_values=2, max_elections=3, max_restarts=1,
                   msg_slots=32)
    return (cached_model(p),
            ("LeaderHasAllAckedValues", "NoLogDivergence"),
            dict(chunk=4096, frontier_cap=1 << 18, seen_cap=1 << 22,
                 warm_depth=14,
                 # guard-first apply budgets (per-state units, chunk-
                 # aggregate): per-group enabled maxima measured on the
                 # ENGINE's own frontier partitioning (DeviceBFS
                 # checkpoints at every depth 0..14, sliced into the
                 # same 4096-lane chunks, guards1 per chunk) were
                 # Restart 2.2009 (depth 12-13 — out-of-engine loops
                 # that only sample the deepest wave see 2.076 and
                 # under-budget it), RequestVote 1.230, BecomeLeader
                 # 0.178, ClientRequest 0.976, AdvanceCommitIndex
                 # 0.104, AppendEntries 0.933, HandleMessage 5.647;
                 # each budget rounds up to the next 1/64 with ~2-5%
                 # slack (11.5/state, 47104 lanes vs 229376 dense) —
                 # the warm run aborts loudly if a wave ever exceeds
                 valid_per_group={
                     "Restart": 2.25, "RequestVote": 1.25,
                     "BecomeLeader": 0.1875, "ClientRequest": 1.0,
                     "AdvanceCommitIndex": 0.109375,
                     "AppendEntries": 0.953125, "HandleMessage": 5.75,
                 }))


def _model_fsync():
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.utils.cfg import parse_cfg

    s = build_from_cfg(parse_cfg(f"{REF}/raft-and-fsync/RaftFsync.cfg"),
                       msg_slots=40)
    return s.model, s.invariants, dict(chunk=2048, frontier_cap=1 << 18,
                                       seen_cap=1 << 22, warm_depth=11)


def _model_raft5():
    from raft_tpu.models.raft import RaftParams, cached_model

    p = RaftParams(n_servers=5, n_values=5, max_elections=4, max_restarts=0,
                   msg_slots=64)
    return (cached_model(p),
            ("LeaderHasAllAckedValues", "NoLogDivergence"),
            # depth 10: past the all-tied early waves — deep runs live
            # here. Heavy-tie lanes drain through the adaptive blocked
            # tier 3 (ops/symmetry.py): tie-group-local tables for the
            # enumerable patterns, full S! only for all-tied lanes; no
            # static compaction budget, no whole-batch cond fallback.
            dict(chunk=2048, frontier_cap=1 << 19, seen_cap=1 << 23,
                 warm_depth=10,
                 # measured per-group maxima to depth 10 (per-state
                 # units): RequestVote 2.67, HandleMessage 15.46,
                 # ClientRequest 0.10, AppendEntries 0.09, BecomeLeader
                 # 0.008, Restart/AdvanceCommitIndex 0 (max_restarts=0
                 # disables Restart; tiny nonzero budgets keep the
                 # zero-measured groups abort-safe)
                 valid_per_group={
                     "Restart": 0.03125, "RequestVote": 3.0,
                     "BecomeLeader": 0.0625, "ClientRequest": 0.15625,
                     "AdvanceCommitIndex": 0.03125,
                     "AppendEntries": 0.125, "HandleMessage": 16.0,
                 }))


WL = {"raft3": _model_raft3, "fsync": _model_fsync, "raft5": _model_raft5}


def _emit_micro_md():
    """PROFILE.md section summarizing EMIT_MICRO.json (emit-strategy
    microbench, `python scripts/emit_micro.py`) when it exists — the
    reproducible form of the capacity-sized-scatter-penalty claim the
    emit-append rewrite rests on."""
    path = os.path.join(ROOT, "EMIT_MICRO.json")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        em = json.load(f)
    m = em["meta"]
    md = ["## emit microbench (scripts/emit_micro.py)",
          "",
          f"Device: {m['device']} ({m['when']}), W={m['w']}, "
          f"density={m['density']}, reps={m['reps']}. One chunk's",
          "survivor emit into a frontier-shaped i32 buffer, by strategy:",
          "retired full-capacity scatter vs production compact+append",
          "vs sort-based compaction. All variants donate the buffer.",
          "Read with the per-workload `scatter` rows above: a DONATED",
          "scatter a backend can alias updates in place and can bench",
          "near the append (CPU does); the penalty appears whenever the",
          "scatter output cannot alias its operand and the lowering",
          "materializes the full capacity-sized buffer — the profile's",
          "self-contained `scatter` row measures exactly that, and it",
          "is FCAP-bound while the append stays VC-bound.",
          "",
          "| VC | FCAP | scatter ms | compact+DUS ms | sort ms | scatter/compact |",
          "|---:|---:|---:|---:|---:|---:|"]
    for r in em["rows"]:
        md.append(f"| {r['vc']} | {r['fcap']} | {r['scatter_full_ms']} "
                  f"| {r['compact_dus_ms']} | {r['sort_emit_ms']} "
                  f"| {r['scatter_over_compact']}x |")
    md.append("")
    return md


def _expand_micro_md():
    """PROFILE.md section summarizing EXPAND_MICRO.json (dense vs
    guard-first expansion microbench, `python scripts/expand_micro.py`)
    when it exists — the reproducible form of the expand-wall claim the
    sparse expansion rests on."""
    path = os.path.join(ROOT, "EXPAND_MICRO.json")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        em = json.load(f)
    m = em["meta"]
    md = ["## expand microbench (scripts/expand_micro.py)",
          "",
          f"Device: {m['device']} ({m['when']}), model={m['model']} "
          f"{m['params']}, reps={m['reps']}. One chunk's successor",
          "expansion on a real reachable frontier, three schedules that",
          "produce bit-identical compacted blocks: `dense mat` runs the",
          "full kernels and MATERIALIZES the [chunk, A, W] successor",
          "tensor (what the legacy engines paid while bag_put carried a",
          "lax.sort — sorts block producer fusion); `dense` jits the",
          "same kernels together with the compaction gather, which the",
          "backend now fuses into an implicit sparse schedule (kernels",
          "computed only for gathered rows — fast, but a contract-free",
          "fusion heuristic); guard-first (guards + apply) is the",
          "EXPLICIT sparse schedule: DCE guard pass + per-group",
          "budgeted apply over the enabled worklist, with overflow",
          "abort and density gauges instead of silent densification.",
          "`vs mat` is guard-first against the materialized baseline",
          "(the lane-ratio claim); `vs fused` against the fused one —",
          "near or below 1x wherever fusion already sparsifies, which",
          "is the honest bookkeeping cost of making the schedule a",
          "guarantee. `vpg` is the apply budget in per-state units",
          "(`loose` = the overflow-impossible bound).",
          "",
          "| chunk | vpg | plan lanes | dense lanes | density "
          "| dense ms | dense mat ms | guards ms | apply ms "
          "| vs fused | vs mat |",
          "|---:|---:|---:|---:|---:|---:|---:|---:|---:|---:|---:|"]
    for r in em["rows"]:
        md.append(f"| {r['chunk']} | {r['vpg']} | {r['plan_lanes']} "
                  f"| {r['dense_lanes']} | {r['density']} "
                  f"| {r['dense_ms']} | {r.get('dense_mat_ms', '-')} "
                  f"| {r['guards_ms']} | {r['apply_ms']} "
                  f"| {r['speedup']}x | {r.get('speedup_mat', '-')}x |")
    md.append("")
    return md


def main():
    argv = sys.argv[1:]
    if "--platform" in argv:
        i = argv.index("--platform")
        import jax

        jax.config.update("jax_platforms", argv[i + 1])
        del argv[i:i + 2]  # drop the flag AND its value
    md_only = "--md-only" in argv
    args = [a for a in argv if not a.startswith("--")]
    from raft_tpu.checker.profile import profile_stages, render

    pick = args or list(WL)
    out_json = os.path.join(ROOT, "PROFILE.json")
    results = {}
    if os.path.exists(out_json):
        with open(out_json) as f:
            results = json.load(f)
    done = []
    if md_only:  # rebuild the md from results already on disk; keep the
        # recorded measurement device/time
        pick, done = [], [n for n in pick if n in results]
    else:
        import jax

        results["meta"] = {"device": str(jax.devices()[0]),
                           "when": time.strftime("%Y-%m-%d %H:%M:%S")}
    for name in pick:
        if name == "fsync" and not os.path.isdir(REF):
            print("=== fsync === skipped: no /root/reference checkout "
                  "(RaftFsync.cfg is reference-only)", flush=True)
            continue
        model, invs, kw = WL[name]()
        print(f"=== {name} ===", flush=True)
        from raft_tpu.obs import Telemetry

        tel = Telemetry()  # in-memory: the manifest event is the
        # workload's provenance record (ident/hashv/memo geometry)
        prof = profile_stages(model, invariants=invs, symmetry=True,
                              telemetry=tel, **kw)
        man = next((e for e in tel.events if e["event"] == "manifest"), {})
        prof["manifest"] = {
            k: man.get(k) for k in
            ("ident", "hashv", "canon_memo_cap", "device", "platform")
        }
        results[name] = prof
        done.append(name)
        print(render(prof), flush=True)
        with open(out_json, "w") as f:
            json.dump(results, f, indent=1)

    md = ["# Stage-level profile of the DeviceBFS hot loop",
          "",
          "This file attributes time WITHIN a wave, offline, by",
          "re-running each pipeline stage in isolation. For live",
          "wall-clock numbers — per-wave seconds, sustained distinct/s,",
          "memo hit rate over a real run — use the runtime telemetry",
          "stream instead (`--progress` / `--metrics-out`; README",
          "\"Observability\").",
          "",
          "The live counterpart of THIS table is the wave-timeline",
          "observatory (`--timeline[=EVERY_N]`, `timeline` events,",
          "rendered by `scripts/obs_report.py`): every Nth wave of a",
          "real run is re-dispatched as separately timed stages, so its",
          "stage shares include the cross-stage effects isolation hides",
          "(cache reuse, host overlap, real frontier mix). Trust THIS",
          "file for per-stage isolation — which kernel is slow and why;",
          "trust the timeline shares for where a real run's wall clock",
          "actually goes. When the two disagree, the gap itself is the",
          "finding (usually dispatch overlap or a frontier mix the",
          "offline workloads don't reproduce).",
          "",
          f"Device: {results['meta']['device']} "
          f"({results['meta']['when']}). Produced by "
          "`python scripts/profile_workloads.py`; stage semantics in "
          "`raft_tpu/checker/profile.py`. Shares are of the per-chunk "
          "stage sum (fused_chunk / lsm_merge_2r0 are separate rows: "
          "the fused production program and one R0+R0 run merge).",
          "",
          "Caveats: (a) of the three canon rows only `canon` — the",
          "memoized mixed hit/miss path against the warm run's live",
          "memo table, what a production chunk actually pays — is in",
          "the stage sum. `canon_memo_hit` (the pure-hit floor on a",
          "table already holding every key of the chunk) and",
          "`canon_tier3_local` (the tier-3 resolve alone) re-measure",
          "sub-paths inside `canon`; they are reported for visibility",
          "and excluded from the sum, which would otherwise",
          "triple-count canon work. (b) `emit_append` is the",
          "production emit (round 6: dense-prefix compaction + one",
          "donated cursor append per buffer); `scatter` is the RETIRED",
          "pre-round-6 emit (full-capacity arbitrary-index scatters),",
          "kept as a diagnostic row so regenerated profiles show",
          "old-vs-new emit cost side by side — it is excluded from the",
          "stage sum. (c) tier 3 has no static compaction budget",
          "anymore: both the tie-group-local and the full-table",
          "buckets drain in fixed-size blocks of an adaptive-trip",
          "while_loop, so there is no budget-dependent capture skew to",
          "correct for (the retired B//16-vs-B//8 caveat). (d) on the",
          "tunnel-connected TPU backend, long processes develop a",
          "~100+ ms per-dispatch floor; every stage row pays it once,",
          "so the table's `net ms` column (ms - null_dispatch) is the",
          "comparable number and all shares are computed over it — on",
          "floor-dominated tables (e.g. a tunnel-profiled fsync) the",
          "raw ms column is mostly dispatch latency. (e) for models",
          "with the guard-first sparse expansion (models/base.py),",
          "`guards` + `apply` are the production expansion and the",
          "dense `expand` row joins the diagnostic set (excluded from",
          "the stage sum, like `scatter`), kept so old-vs-new expansion",
          "cost stays side by side; `per_wave_s.expand_share_of_stage_",
          "sum` tracks the combined production share. Note the isolated",
          "`expand` row must materialize the [chunk, A, W] successor",
          "tensor; inside a fused program that ends in the compaction",
          "gather, a backend whose fusion can chase the gather into an",
          "elementwise producer computes kernels only for gathered rows",
          "— the expand microbench at the bottom separates the two",
          "dense baselines and prices guard-first against both.",
          ""]
    for name in done:
        md += [f"## {name}", "", "```", render(results[name]), "```", ""]
    md += _emit_micro_md()
    md += _expand_micro_md()
    with open(os.path.join(ROOT, "PROFILE.md"), "w") as f:
        f.write("\n".join(md))
    print("wrote PROFILE.md / PROFILE.json")


if __name__ == "__main__":
    main()
