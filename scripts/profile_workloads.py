"""Stage-level profile of the three verdict workloads -> PROFILE.md.

Workloads (round-3 verdict Next #1):
  raft3   standard-raft Raft.cfg           (3 servers, 6 perms)
  fsync   raft-and-fsync RaftFsync.cfg     (3 servers, 6 perms)
  raft5   Raft 5s/5v/MaxTerm5 (BENCH row2) (5 servers, 120 perms)

Usage: python scripts/profile_workloads.py [raft3 fsync raft5] [--platform cpu]
Writes PROFILE.md + PROFILE.json at the repo root.
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
REF = "/root/reference/specifications"


def _model_raft3():
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.utils.cfg import parse_cfg

    s = build_from_cfg(parse_cfg(f"{REF}/standard-raft/Raft.cfg"), msg_slots=32)
    return s.model, s.invariants, dict(chunk=4096, frontier_cap=1 << 18,
                                       seen_cap=1 << 22, warm_depth=14)


def _model_fsync():
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.utils.cfg import parse_cfg

    s = build_from_cfg(parse_cfg(f"{REF}/raft-and-fsync/RaftFsync.cfg"),
                       msg_slots=40)
    return s.model, s.invariants, dict(chunk=2048, frontier_cap=1 << 18,
                                       seen_cap=1 << 22, warm_depth=11)


def _model_raft5():
    from raft_tpu.models.raft import RaftParams, cached_model

    p = RaftParams(n_servers=5, n_values=5, max_elections=4, max_restarts=0,
                   msg_slots=64)
    return (cached_model(p),
            ("LeaderHasAllAckedValues", "NoLogDivergence"),
            # depth 10: past the all-tied early waves (tie rate ~35%
            # with groups <= 2 dominating; at depth 9 heavy-tie lanes
            # still exceed the B//16 compaction budget and the cond
            # falls back to the full table) — deep runs live here
            dict(chunk=2048, frontier_cap=1 << 19, seen_cap=1 << 23,
                 warm_depth=10))


WL = {"raft3": _model_raft3, "fsync": _model_fsync, "raft5": _model_raft5}


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    if "--platform" in sys.argv:
        plat = sys.argv[sys.argv.index("--platform") + 1]
        import jax

        jax.config.update("jax_platforms", plat)
    from raft_tpu.checker.profile import profile_stages, render

    pick = args or list(WL)
    out_json = os.path.join(ROOT, "PROFILE.json")
    results = {}
    if os.path.exists(out_json):
        with open(out_json) as f:
            results = json.load(f)
    import jax

    results["meta"] = {"device": str(jax.devices()[0]),
                       "when": time.strftime("%Y-%m-%d %H:%M:%S")}
    for name in pick:
        model, invs, kw = WL[name]()
        print(f"=== {name} ===", flush=True)
        prof = profile_stages(model, invariants=invs, symmetry=True, **kw)
        results[name] = prof
        print(render(prof), flush=True)
        with open(out_json, "w") as f:
            json.dump(results, f, indent=1)

    md = ["# Stage-level profile of the DeviceBFS hot loop",
          "",
          f"Device: {results['meta']['device']} "
          f"({results['meta']['when']}). Produced by "
          "`python scripts/profile_workloads.py`; stage semantics in "
          "`raft_tpu/checker/profile.py`. Shares are of the per-chunk "
          "stage sum (fused_chunk / lsm_merge_2r0 are separate rows: "
          "the fused production program and one level-0 LSM run merge).",
          ""]
    for name in pick:
        md += [f"## {name}", "", "```", render(results[name]), "```", ""]
    with open(os.path.join(ROOT, "PROFILE.md"), "w") as f:
        f.write("\n".join(md))
    print("wrote PROFILE.md / PROFILE.json")


if __name__ == "__main__":
    main()
