"""Compare u64 splitmix hashing vs u32-pair hashing at chunk geometry."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=10):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


B, K, P = 65536, 190, 6
rng = np.random.default_rng(0)
v = jnp.asarray(rng.integers(0, 1 << 30, (B, K), dtype=np.int32))

from raft_tpu.ops.hashing import mix64, _C1, _C2

M1 = np.uint32(0x85EBCA6B)
M2 = np.uint32(0xC2B2AE35)


def mix32(z):
    z = (z ^ (z >> np.uint32(16))) * M1
    z = (z ^ (z >> np.uint32(13))) * M2
    return z ^ (z >> np.uint32(16))


@jax.jit
def h64(v):
    acc = jnp.zeros((B,), jnp.uint64)
    pos = jnp.arange(K, dtype=jnp.uint64)
    for p in range(P):
        x = v.astype(jnp.uint64)
        h = mix64(x * _C1 + pos * _C2 + np.uint64(p * 1234567))
        acc = acc ^ jnp.bitwise_xor.reduce(h, axis=-1)
    return acc


@jax.jit
def h32pair(v):
    accA = jnp.zeros((B,), jnp.uint32)
    accB = jnp.zeros((B,), jnp.uint32)
    posA = jnp.arange(K, dtype=jnp.uint32) * np.uint32(0x9E3779B9)
    posB = jnp.arange(K, dtype=jnp.uint32) * np.uint32(0x7FEB352D)
    for p in range(P):
        x = v.astype(jnp.uint32)
        hA = mix32(x * np.uint32(0xCC9E2D51) + posA + np.uint32(p * 77))
        hB = mix32(x * np.uint32(0x1B873593) + posB + np.uint32(p * 101))
        accA = accA ^ jnp.bitwise_xor.reduce(hA, axis=-1)
        accB = accB ^ jnp.bitwise_xor.reduce(hB, axis=-1)
    return accA.astype(jnp.uint64) << np.uint64(32) | accB.astype(jnp.uint64)


t = timeit(h64, v)
print(f"u64 hash xP={P}: {t*1e3:.3f} ms", jax.device_get(h64(v))[0])
t = timeit(h32pair, v)
print(f"u32-pair hash xP={P}: {t*1e3:.3f} ms", jax.device_get(h32pair(v))[0])
