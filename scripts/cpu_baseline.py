"""Strong-CPU-baseline arm for bench.py (round-4 verdict Next #5).

Runs the SAME DeviceBFS engine on the XLA CPU backend (vectorized,
single-core on this host) over the same depth-capped workload, excluding
compile time the same way the TPU arm does. Prints one JSON line:
  {"depth": N, "distinct": N, "seconds": S, "platform": "cpu"}

Invoked as a subprocess because the JAX platform is process-global.
Usage: python scripts/cpu_baseline.py <cfg> <cmp_depth> <chunk> <msg_slots>
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    cfg_path, cmp_depth, chunk, msg_slots = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]))
    import jax

    jax.config.update("jax_platforms", "cpu")
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.checker.device_bfs import DeviceBFS

    cfg = parse_cfg(cfg_path)
    setup = build_from_cfg(cfg, msg_slots=msg_slots)
    dev = DeviceBFS(
        setup.model, invariants=setup.invariants, symmetry=True, chunk=chunk,
        frontier_cap=1 << 18, seen_cap=1 << 22, journal_cap=1 << 22,
    )
    dev.run(max_depth=2)  # compile outside the timed window (same as TPU arm)
    t0 = time.perf_counter()
    res = dev.run(max_depth=cmp_depth)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "depth": res.depth,
        "distinct": res.distinct,
        "depth_counts": res.depth_counts,
        "seconds": round(dt, 2),
        "platform": "cpu",
    }))


if __name__ == "__main__":
    main()
