"""Micro-profile of canonicalization sub-stages at bench geometry.

Fresh-process timings (the tunnel's long-process dispatch floor distorts
stage sums — see bench.py); run as its own process per workload:

    python scripts/canon_micro.py [raft3|raft5]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=5):
    fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) else None
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "raft3"
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
    if which == "raft5":
        cfg.constants["Server"] = ["n1", "n2", "n3", "n4", "n5"]
    setup = build_from_cfg(cfg, msg_slots=32)
    model = setup.model
    canon = __import__(
        "raft_tpu.ops.symmetry", fromlist=["Canonicalizer"]
    ).Canonicalizer.for_model(model, symmetry=True)

    B = 65536
    # realistic-ish states: expand init a few waves on CPU-ish path is slow;
    # just tile init states with random aux jitter in valid ranges is risky.
    # Use real successors: expand init states via model._expand1 a few rounds.
    states = np.asarray(model.init_states())
    rng = np.random.default_rng(0)
    exp = jax.jit(jax.vmap(model._expand1))
    for _ in range(6):
        succs, valid, _r, _o = jax.device_get(exp(jnp.asarray(states)))
        flat = succs.reshape(-1, succs.shape[-1])[valid.reshape(-1)]
        if len(flat) > B:
            flat = flat[rng.choice(len(flat), B, replace=False)]
        states = flat
    reps = int(np.ceil(B / len(states)))
    states = np.tile(states, (reps, 1))[:B]
    view = jnp.asarray(states[:, : canon.VL])
    print(f"{which}: S={canon.S} P={canon.P} VL={canon.VL} "
          f"nonbag={len(canon._nonbag_lanes)} B={B}", flush=True)

    full = jax.jit(canon._fingerprints)
    t = timeit(full, jnp.asarray(states))
    print(f"fingerprints_total: {t*1e3:.1f} ms", flush=True)

    if canon.prune:
        sig = jax.jit(canon._signatures)
        t = timeit(sig, view)
        print(f"signatures: {t*1e3:.1f} ms", flush=True)

    mm = jax.jit(lambda v: canon._masked_min(v, None))
    t = timeit(mm, view)
    print(f"masked_min_full_table (P={canon.P}): {t*1e3:.1f} ms", flush=True)

    # sub-stages of one static perm, x P to compare
    gi0 = canon._gidx
    P = canon.P

    @jax.jit
    def gathers_only(v):
        acc = jnp.zeros((v.shape[0],), jnp.uint64)
        for p in range(P):
            acc = acc ^ v[:, gi0[p]].astype(jnp.uint64).sum(axis=1)
        return acc

    t = timeit(gathers_only, view)
    print(f"row-gathers xP only: {t*1e3:.1f} ms", flush=True)

    @jax.jit
    def hash_only(v):
        acc = jnp.zeros((v.shape[0],), jnp.uint64)
        for _p in range(P):
            acc = acc ^ canon._perm_hash(v)
        return acc

    t = timeit(hash_only, view)
    print(f"perm_hash xP (no gather/remap): {t*1e3:.1f} ms", flush=True)

    @jax.jit
    def bag_only(v):
        acc = jnp.zeros((v.shape[0],), jnp.uint64)
        for _p in range(P):
            acc = acc ^ canon._bag_hash(v)
        return acc

    t = timeit(bag_only, view)
    print(f"bag_hash xP: {t*1e3:.1f} ms", flush=True)

    from raft_tpu.ops.hashing import hash_lanes

    @jax.jit
    def nb_only(v):
        acc = jnp.zeros((v.shape[0],), jnp.uint64)
        for _p in range(P):
            acc = acc ^ hash_lanes(v[:, canon._nonbag_lanes])
        return acc

    t = timeit(nb_only, view)
    print(f"nonbag hash_lanes xP: {t*1e3:.1f} ms", flush=True)

    # remap-only (value remaps w/o gather or hash)
    vm, p2, sg = canon._valmap, canon._pow2sig, canon._sigma

    @jax.jit
    def remap_only(v):
        acc = jnp.zeros((v.shape[0],), jnp.int32)
        for p in range(P):
            vv = v
            if canon._val_lanes.size:
                vl = vv[:, canon._val_lanes]
                vv = vv.at[:, canon._val_lanes].set(vm[p][vl])
            if canon._msg_word_sls:
                words = [vv[:, sl] for sl in canon._msg_word_sls]
                nwords = list(words)
                for fname, kind in canon.msg_perm_spec:
                    val = canon._unpack_key(nwords, fname)
                    if kind == "server":
                        mapped = sg[p][jnp.clip(val, 0, canon.S - 1)]
                    else:
                        mapped = val
                    nwords = canon._replace_key(nwords, fname, mapped)
                for sl, arr in zip(canon._msg_word_sls, nwords):
                    vv = vv.at[:, sl].set(arr)
            acc = acc ^ vv.sum(axis=1)
        return acc

    t = timeit(remap_only, view)
    print(f"value remaps xP (incl .at[].set): {t*1e3:.1f} ms", flush=True)


if __name__ == "__main__":
    main()
