"""Expand-stage structural experiments (round 5).

Times handle_message (the 93 GB/chunk cost-analysis monster) and the
full expand under structural variants:
  - state-outer vmap (production) vs instance-outer vmap
  - msg_slots 32 (bench default) vs 16

Usage: python scripts/expand_exp.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _sync(out):
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "ravel"):
            np.asarray(jax.device_get(leaf.ravel()[:1] if leaf.ndim else leaf))


def timeit(name, fn, *args):
    _sync(fn(*args))
    ts = []
    for _ in range(4):
        t0 = time.perf_counter()
        out = None
        for _ in range(4):
            out = fn(*args)
        _sync(out)
        ts.append((time.perf_counter() - t0) / 4)
    med = sorted(ts)[len(ts) // 2]
    print(f"{name:44s} {med*1e3:9.1f} ms")


def main():
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
    C = 4096

    for slots in (32, 16):
        setup = build_from_cfg(cfg, msg_slots=slots)
        model = setup.model
        M, W = model.p.msg_slots, model.layout.W
        batch = jnp.zeros((C, W), jnp.int32)
        marange = jnp.arange(M, dtype=jnp.int32)

        hm_so = jax.jit(lambda b: jax.vmap(
            lambda s: jax.vmap(lambda m: model._handle_message(s, m))(marange)
        )(b))
        timeit(f"M={slots} handle_message state-outer", hm_so, batch)

        hm_io = jax.jit(lambda b: jax.vmap(
            lambda m: jax.vmap(lambda s: model._handle_message(s, m))(b)
        )(marange))
        timeit(f"M={slots} handle_message instance-outer", hm_io, batch)

        full = jax.jit(lambda b: jax.vmap(model._expand1)(b))
        timeit(f"M={slots} full expand state-outer", full, batch)


if __name__ == "__main__":
    main()
