"""Stage-2 bisect: do canonical fingerprints of the SAME states differ by
batch size on the TPU?  Compares canon.fingerprints over the depth-9 wave's
compacted successors evaluated at 65536-lane batch vs 2048-lane chunks vs
numpy decode-level recomputation of the hash on host.
"""

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.utils.cfg import parse_cfg
from raft_tpu.models.registry import build_from_cfg
from raft_tpu.ops.hashing import U64_MAX
from raft_tpu.ops.symmetry import Canonicalizer

DEPTH = 9

cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
setup = build_from_cfg(cfg, msg_slots=32)
model = setup.model
canon = Canonicalizer.for_model(model, symmetry=True)
W, A = model.layout.W, model.A

expand1 = jax.jit(jax.vmap(model._expand1))
init = model.init_states()
frontier = np.asarray(init)


def host_fps(states):
    return np.array(
        jax.device_get(canon.fingerprints(np.asarray(states))), dtype=np.uint64
    )


seen = set(host_fps(frontier).tolist())
for d in range(DEPTH):
    succs, valid, _r, _o = jax.device_get(expand1(frontier))
    flat = succs.reshape(-1, W)
    v = valid.reshape(-1)
    fps = host_fps(flat)
    nxt = []
    for i in np.nonzero(v)[0]:
        f = int(fps[i])
        if f not in seen:
            seen.add(f)
            nxt.append(flat[i])
    frontier = np.asarray(nxt)

F = len(frontier)
print(f"depth-{DEPTH} frontier: {F}")

# expand the frontier once more (383-batch, same as host loop)
succs, valid, _r, _o = jax.device_get(expand1(frontier))
flat = succs.reshape(-1, W)
v = valid.reshape(-1)
idxs = np.nonzero(v)[0]
cand = flat[idxs]  # [1762, W] the true successor states
n = len(cand)
print("candidates:", n)

# pad to the two batch geometries and fingerprint
def fps_at(width):
    buf = np.zeros((width, W), np.int32)
    buf[:n] = cand
    out = np.array(jax.device_get(canon.fingerprints(buf)), dtype=np.uint64)
    return out[:n]

f_small = fps_at(2048)
f_65k = fps_at(65536)
f_native = host_fps(cand)  # whatever batch n=1762 compiles to

print("65k vs 2048 mismatches:", int((f_65k != f_small).sum()))
print("native vs 2048 mismatches:", int((f_native != f_small).sum()))

bad = np.nonzero(f_65k != f_small)[0]
if len(bad):
    b = bad[0]
    print("first bad lane:", b)
    print("state:", cand[b])
    print("fp small: %016x" % f_small[b], " fp 65k: %016x" % f_65k[b])
    # recompute the same lane alone
    one = np.array(jax.device_get(canon.fingerprints(cand[b : b + 1])), dtype=np.uint64)
    print("fp alone: %016x" % one[0])
