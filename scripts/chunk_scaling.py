"""Does per-chunk cost scale with chunk size, or is it op-launch bound?

Times the PRODUCTION fused chunk program at several chunk sizes on the
same warmed raft3 frontier (pipelined 4-deep, device_get sync — the
timer that matches wave walls). If cost is sublinear in C, the cheapest
deep-run multiplier is simply a bigger chunk.

Usage: python scripts/chunk_scaling.py [sizes...]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

SIZES = [int(a) for a in sys.argv[1:]] or [1024, 4096, 16384]


def _sync(out):
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "ravel"):
            np.asarray(jax.device_get(leaf.ravel()[:1] if leaf.ndim else leaf))


def main():
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.checker.device_bfs import DeviceBFS

    cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
    setup = build_from_cfg(cfg, msg_slots=32)

    # one warm run to get a real frontier (depth 14: 6608 states)
    import tempfile

    dev0 = DeviceBFS(setup.model, invariants=setup.invariants, symmetry=True,
                     chunk=1024, frontier_cap=1 << 17, seen_cap=1 << 21)
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "w.npz")
        dev0.run(max_depth=14, checkpoint_path=ck)
        d = np.load(ck, allow_pickle=False)
        frontier_h = np.asarray(d["frontier"])
        seen_h = np.asarray(d["seen"])
    print(f"warm frontier {len(frontier_h)}, seen {len(seen_h)}")

    for C in SIZES:
        dev = DeviceBFS(setup.model, invariants=setup.invariants,
                        symmetry=True, chunk=C,
                        frontier_cap=max(1 << 18, C), seen_cap=1 << 21)
        W = dev.W
        # round-5 seen design: one sorted U64_MAX-padded run
        dev._seed_seen(np.sort(seen_h.astype(np.uint64)))
        occ_dev = dev._occ_one
        runs = (dev._seen,)
        fh = np.zeros((dev.FCAP + 1, W), np.int32)
        n = min(len(frontier_h), dev.FCAP)
        fh[:n] = frontier_h[:n]
        frontier = jnp.asarray(fh)

        def once_args():
            nb = jnp.zeros((dev.FCAP + 1, W), jnp.int32)
            jp = jnp.zeros((dev.JCAP + 1,), jnp.int32)
            jc = jnp.zeros((dev.JCAP + 1,), jnp.int32)
            viol = jnp.full((max(1, len(dev.invariants)),),
                            np.int32(2**31 - 1), jnp.int32)
            stats = jnp.zeros((6,), jnp.int64)
            memo = dev._memo.reset()
            cov = jnp.zeros((dev.n_actions, 3), jnp.int64)
            return [frontier, nb, jp, jc, viol, stats, memo, cov,
                    np.int32(0), np.int32(min(n, C)), np.int32(0), occ_dev,
                    jnp.asarray(True), *runs]

        t0 = time.perf_counter()
        _sync(dev._chunk_fn(*once_args()))
        compile_s = time.perf_counter() - t0
        ts = []
        for _ in range(5):
            argsets = [once_args() for _ in range(4)]
            t0 = time.perf_counter()
            out = None
            for a in argsets:
                out = dev._chunk_fn(*a)
            _sync(out)
            ts.append((time.perf_counter() - t0) / 4)
        med = sorted(ts)[len(ts) // 2]
        print(f"C={C:6d} VC={dev.VC:7d}: {med*1e3:8.1f} ms/chunk "
              f"({med*1e6/C:6.1f} us/state)  compile {compile_s:.1f}s")


if __name__ == "__main__":
    main()
