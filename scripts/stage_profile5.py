"""Round-5 stage re-profile with a trustworthy timer.

block_until_ready does not reliably wait on the axon tunnel backend
(scripts/prim_micro.py: a null dispatch reads 0.03 ms via
block_until_ready but 117 ms via device_get), so this harness re-times
the profile.py stages with a device_get sync AND an inner-pipelined
variant (N back-to-back dispatches, one sync, divide by N) that cancels
the tunnel floor — the number that matches production wave walls, where
dispatches pipeline.

Usage: python scripts/stage_profile5.py [raft3|raft5|fsync]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import raft_tpu.checker.profile as prof_mod


def _sync(out):
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "ravel"):
            np.asarray(jax.device_get(leaf.ravel()[:1] if leaf.ndim else leaf))


def _time(fn, *args, reps: int = 5, inner: int = 1) -> float:
    _sync(fn(*args))  # compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = None
        for _ in range(4):  # pipeline 4 dispatches, one sync
            out = fn(*args)
        _sync(out)
        ts.append((time.perf_counter() - t0) / 4)
    return float(np.median(ts))


prof_mod._time = _time


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "raft3"
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg

    base = "/root/reference/specifications"
    if which == "raft3":
        cfg = parse_cfg(f"{base}/standard-raft/Raft.cfg")
        setup = build_from_cfg(cfg, msg_slots=32)
        kw = dict(chunk=4096, frontier_cap=1 << 18, seen_cap=1 << 21,
                  warm_depth=14)
    elif which == "raft5":
        cfg = parse_cfg(f"{base}/standard-raft/Raft.cfg")
        cfg.constants["InitServerCount"] = 5
        cfg.constants["Server"] = ["s1", "s2", "s3", "s4", "s5"]
        setup = build_from_cfg(cfg, msg_slots=64)
        kw = dict(chunk=2048, frontier_cap=1 << 21, seen_cap=1 << 22,
                  warm_depth=10, max_frontier_cap=1 << 22)
    else:
        cfg = parse_cfg(f"{base}/standard-raft-fsync/RaftFsync.cfg")
        setup = build_from_cfg(cfg, msg_slots=32)
        kw = dict(chunk=2048, frontier_cap=1 << 18, seen_cap=1 << 21,
                  warm_depth=11)

    out = prof_mod.profile_stages(
        setup.model, invariants=setup.invariants, symmetry=True, **kw
    )
    print(prof_mod.render(out))


if __name__ == "__main__":
    main()
